"""dflint core: file model, findings, pragmas, and the checker runner.

The analyzer mirrors what Go's ``vet``/``-race`` buy the reference
Dragonfly2: project-specific invariants enforced by AST inspection, not
convention.  Each checker module exposes ``RULE`` (the ``DFxxx`` id),
``TITLE`` and ``check(module) -> Iterable[Finding]``; the runner parses
each file once into a :class:`Module` (tree + parent/qualname maps +
pragma table) and hands it to every registered checker.

Suppression layers, narrowest wins:

- ``# dflint: disable=DF001`` (or ``disable=DF001,DF004``) on the
  reported line — point suppression for a reviewed, accepted site;
- ``# dflint: disable-file=DF003`` anywhere in the file — the whole
  file opts out of one rule (e.g. a simulator that legitimately sleeps);
- ``tools/dflint/baseline.toml`` — accepted pre-existing findings keyed
  by ``RULE:relpath:qualname`` so history doesn't block the gate while
  NEW findings in the same file still fail.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

_PRAGMA = re.compile(
    r"#\s*dflint:\s*(disable|disable-file)\s*=\s*([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``key()`` is the baseline identity: rule +
    file + enclosing qualname — line numbers shift too easily to pin."""

    rule: str
    path: str          # repo-relative, "/"-separated
    line: int
    col: int
    message: str
    qual: str          # enclosing "Class.method" / "function" / "<module>"

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.qual}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.qual}] {self.message}"


class Module:
    """One parsed source file plus the lookup tables checkers share."""

    def __init__(self, path: Path, relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        # Parent links + dotted qualnames for every function/class scope.
        self.parents: Dict[int, ast.AST] = {}
        self.qualnames: Dict[int, str] = {}
        self._scope_of: Dict[int, Optional[ast.AST]] = {}
        self._index(self.tree, None, [])
        # rule -> set of suppressed physical lines; "" key = whole file.
        self.pragmas: Dict[str, set] = {}
        self.file_pragmas: set = set()
        self._scan_pragmas()

    # -- structure ----------------------------------------------------------

    def _index(self, node: ast.AST, scope: Optional[ast.AST], stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            self.parents[id(child)] = node
            self._scope_of[id(child)] = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                qual = stack + [child.name]
                self.qualnames[id(child)] = ".".join(qual)
                self._index(child, child, qual)
            else:
                self._index(child, scope, stack)

    def qualname(self, node: ast.AST) -> str:
        """Dotted name of the scope enclosing ``node`` (itself, when the
        node IS a def/class)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if id(cur) in self.qualnames:
                return self.qualnames[id(cur)]
            cur = self.parents.get(id(cur))
        return "<module>"

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self._scope_of.get(id(node))
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            cur = self._scope_of.get(id(cur))
        return cur

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = self._scope_of.get(id(node))
        while cur is not None and not isinstance(cur, ast.ClassDef):
            cur = self._scope_of.get(id(cur))
        return cur

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    # -- pragmas ------------------------------------------------------------

    def _scan_pragmas(self) -> None:
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            comments = [
                (tok.start[0], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):
            comments = [
                (i + 1, line)
                for i, line in enumerate(self.lines)
                if "#" in line
            ]
        for lineno, text in comments:
            m = _PRAGMA.search(text)
            if not m:
                continue
            kind, rules = m.group(1), [r.strip() for r in m.group(2).split(",")]
            for rule in rules:
                if kind == "disable-file":
                    self.file_pragmas.add(rule)
                else:
                    self.pragmas.setdefault(rule, set()).add(lineno)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_pragmas:
            return True
        return line in self.pragmas.get(rule, set())

    # -- finding constructor ------------------------------------------------

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            qual=self.qualname(node),
        )


# ---------------------------------------------------------------------------
# Shared AST helpers (the checkers' common vocabulary)
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(k.arg == name for k in call.keywords)


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)   # unparseable files


def collect_files(paths: Iterable[Path], root: Path) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    # De-dup while keeping order; skip generated protobuf code.
    seen = set()
    files = []
    for f in out:
        rf = f.resolve()
        if rf in seen or f.name.endswith("_pb2.py"):
            continue
        seen.add(rf)
        files.append(f)
    return files


def relpath_of(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def load_module(path: Path, root: Path) -> Module:
    source = path.read_text(encoding="utf-8")
    return Module(path, relpath_of(path, root), source)


def run_checkers(module: Module, checkers=None) -> List[Finding]:
    """All non-suppressed findings for one parsed module."""
    from .checkers import CHECKERS

    out: List[Finding] = []
    for checker in checkers if checkers is not None else CHECKERS:
        for f in checker.check(module):
            if not module.suppressed(f.rule, f.line):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def run_paths(paths: Iterable[Path], root: Path, checkers=None) -> RunResult:
    result = RunResult()
    for path in collect_files(paths, root):
        try:
            module = load_module(path, root)
        except (SyntaxError, UnicodeDecodeError) as exc:
            result.errors.append(f"{relpath_of(path, root)}: {exc}")
            continue
        result.findings.extend(run_checkers(module, checkers))
    return result


def _check_one_file(args: Tuple[str, str, Optional[Tuple[str, ...]]]):
    """Worker for ``run_paths_parallel`` — module-level so it pickles.
    Checker objects don't cross the process boundary; rule names do."""
    path_str, root_str, rule_names = args
    from .checkers import CHECKERS

    checkers = (
        None if rule_names is None
        else [c for c in CHECKERS if c.RULE in rule_names]
    )
    path, root = Path(path_str), Path(root_str)
    try:
        module = load_module(path, root)
    except (SyntaxError, UnicodeDecodeError) as exc:
        return [], f"{relpath_of(path, root)}: {exc}"
    return run_checkers(module, checkers), None


def run_paths_parallel(
    paths: Iterable[Path], root: Path, checkers=None, jobs: int = 1
) -> RunResult:
    """Per-file checking fanned out over ``jobs`` worker processes.
    Only the embarrassingly-parallel per-file rules run here — the
    whole-program analyses (DF008+) stay single-pass in the caller.
    Findings come back deterministic: workers are mapped in collection
    order and results re-sorted the same way as the serial path."""
    files = collect_files(paths, root)
    if jobs <= 1 or len(files) < 2:
        return run_paths(paths, root, checkers)
    rule_names = (
        None if checkers is None else tuple(c.RULE for c in checkers)
    )
    work = [(str(f), str(root), rule_names) for f in files]
    result = RunResult()
    import concurrent.futures

    try:
        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(jobs, len(files))
        ) as pool:
            for findings, error in pool.map(_check_one_file, work):
                result.findings.extend(findings)
                if error is not None:
                    result.errors.append(error)
    except (OSError, concurrent.futures.process.BrokenProcessPool):
        # Constrained environments (no /dev/shm, fork limits): the
        # parallel path is an optimization, never a correctness gate.
        return run_paths(paths, root, checkers)
    return result
