"""Whole-program concurrency analysis for dflint (DF008 / DF009).

The per-file checkers (DF001-DF007) see one AST at a time; the invariants
that kill a threaded serving stack — an RPC issued while a mutex is held,
two subsystems acquiring the same pair of locks in opposite orders — only
exist *between* files.  This module builds the project-wide view:

- a **symbol table** over every module (imports incl. relative ones,
  module functions, classes with MRO, module-level variables and their
  inferred types, ``from x import f as g`` aliasing, ``g = f`` aliases);
- an **intra-project call graph**: plain calls, ``self._x()`` /
  ``cls._x()`` method dispatch (through project-resolvable base classes),
  ``self._attr.method()`` via attribute-type inference (constructor
  calls, annotated constructor parameters, chained attributes like
  ``self._b._mu``), ``mod.CONST.method()`` via module-variable types,
  local-variable types, ``super().m()``, and decorator-wrapped functions
  (a decorated ``def`` still binds its name — calls resolve to the body);
- a **lock model**: every ``threading.Lock`` / ``RLock`` / ``Condition``
  creation is a *lock class* keyed ``relpath:Owner.attr`` (or
  ``relpath:<module>.NAME`` / ``relpath:func.<local>var``), with its
  creation call sites recorded so the dynamic witness
  (``dragonfly2_tpu.utils.dflock``) can map runtime locks back to static
  identities.  ``threading.Condition(self._mu)`` aliases the wrapped
  lock: acquiring the condition IS acquiring ``_mu``.

On top of that, two rule families:

**DF008 — blocking-under-lock.**  Transitively through the call graph, no
mutex may be held across an indefinitely-blocking operation: network I/O
(``retry_call``, ``urlopen``, raw socket ``connect/accept/recv*/sendall``),
``queue.get()`` / ``Thread.join()`` / ``Event.wait()`` / ``Future.result()``
without a timeout, subprocess waits, ``serve_forever``.  A
``Condition.wait()`` releases its own lock while blocked, so only *other*
held locks are reported for it.  Suppression is the usual inline pragma
(``# dflint: disable=DF008`` with a reviewed justification) on the
reported line — the call site inside the critical section.

**DF009 — lock-order inversion.**  Every acquisition of lock B while lock
A is held (directly nested ``with`` or transitively via calls) is an edge
A→B in the global lock-ordering graph.  A cycle means two call paths can
deadlock; the finding names the cycle and the source line of every edge.
A ``# dflint: disable=DF009`` pragma on an edge's source line removes the
edge (a reviewed ordering exception), not just the report.  Self-edges
(same lock class nested, e.g. two instances of one container type) are
kept in the graph for witness parity but never reported as cycles — the
analyzer cannot distinguish instances.

The analysis is deliberately over-approximate on *edges* (a call graph
edge that can never execute still contributes) and under-approximate on
*resolution* (an attribute it cannot type silently contributes nothing).
The dynamic lock witness closes the second gap: every acquisition-order
edge observed at runtime during the tier-1 suite must be present here, so
a resolver blind spot is a test failure, not silent rot.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, collect_files, dotted, load_module

RULE_BLOCKING = "DF008"
TITLE_BLOCKING = "indefinitely-blocking operation while holding a lock"
RULE_ORDER = "DF009"
TITLE_ORDER = "lock-order inversion (deadlock-capable cycle)"

_LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

# Leaf method names that are blocking network/socket operations no matter
# the arguments (a timeout on a socket op bounds one syscall, not the
# stall it causes for every thread queued on the held lock).
_SOCKET_LEAVES = {"accept", "recv", "recvfrom", "recv_into", "sendall", "connect"}
_NET_LEAVES = {"retry_call", "urlopen"}
_SUBPROCESS_CALLS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}
# Dotted prefixes whose leaves collide with the socket set but are not
# sockets (sqlite3.connect is CPU+disk, not a peer).
_NOT_SOCKET_PREFIXES = ("sqlite3.",)


@dataclass
class LockInfo:
    """One lock *class*: all locks created by one owner attribute/name."""

    key: str                       # "relpath:Owner.attr" — stable identity
    kind: str                      # Lock | RLock | Condition
    sites: List[Tuple[str, int]] = field(default_factory=list)
    wraps: Optional["LockInfo"] = None   # Condition(explicit_lock)

    def base(self) -> "LockInfo":
        cur = self
        seen = set()
        while cur.wraps is not None and id(cur) not in seen:
            seen.add(id(cur))
            cur = cur.wraps
        return cur


@dataclass
class Block:
    """One (transitive) blocking operation."""

    desc: str
    releases: frozenset            # lock keys the op releases while blocked
    chain: str                     # "f -> g -> queue.get()" for the message


@dataclass
class Edge:
    src: str
    dst: str
    relpath: str
    line: int
    chain: str


class ClassInfo:
    def __init__(self, minfo: "ModuleInfo", node: ast.ClassDef) -> None:
        self.module = minfo
        self.node = node
        self.name = node.name
        self.base_exprs: List[str] = [d for d in (dotted(b) for b in node.bases) if d]
        self.bases: List["ClassInfo"] = []           # resolved in link phase
        self.children: List["ClassInfo"] = []        # direct subclasses (link phase)
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.attr_type_exprs: Dict[str, Tuple[Tuple[str, ...], ast.AST]] = {}  # attr -> (dotted class exprs, site)
        self.attr_pending: List[Tuple[str, ast.Call, ast.FunctionDef]] = []
        self.attr_types: Dict[str, "ClassInfo"] = {}
        self.attr_locks: Dict[str, LockInfo] = {}
        self._cond_aliases: Dict[str, str] = {}      # cv attr -> wrapped attr name

    # -- MRO-ish lookup (simple linearization, project classes only) --------

    def mro(self) -> List["ClassInfo"]:
        out: List[ClassInfo] = []
        stack: List[ClassInfo] = [self]
        seen: Set[int] = set()
        while stack:
            c = stack.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            out.append(c)
            stack.extend(c.bases)
        return out

    def find_method(self, name: str) -> Optional[Tuple["ClassInfo", ast.FunctionDef]]:
        for c in self.mro():
            if name in c.methods:
                return c, c.methods[name]
        return None

    def descendants(self) -> List["ClassInfo"]:
        out: List[ClassInfo] = []
        stack = list(self.children)
        seen: Set[int] = set()
        while stack:
            c = stack.pop()
            if id(c) in seen:
                continue
            seen.add(id(c))
            out.append(c)
            stack.extend(c.children)
        return out

    def find_methods(self, name: str) -> List[Tuple["ClassInfo", ast.FunctionDef]]:
        """Virtual dispatch: the statically-typed method plus every
        project-subclass override (the runtime object may be any of
        them — KVTable.put must resolve to the backends that lock)."""
        out: List[Tuple[ClassInfo, ast.FunctionDef]] = []
        seen: Set[int] = set()
        hit = self.find_method(name)
        if hit is not None:
            out.append(hit)
            seen.add(id(hit[1]))
        for sub in self.descendants():
            h = sub.find_method(name)
            if h is not None and id(h[1]) not in seen:
                seen.add(id(h[1]))
                out.append(h)
        return out

    def attr_lock(self, name: str) -> Optional[LockInfo]:
        for c in self.mro():
            if name in c.attr_locks:
                return c.attr_locks[name]
        return None

    def attr_type(self, name: str) -> Optional["ClassInfo"]:
        for c in self.mro():
            if name in c.attr_types:
                return c.attr_types[name]
        return None


class FuncInfo:
    def __init__(
        self,
        minfo: "ModuleInfo",
        node: ast.FunctionDef,
        cls: Optional[ClassInfo],
        qual: str,
    ) -> None:
        self.module = minfo
        self.node = node
        self.cls = cls
        self.qual = qual
        self.key = f"{minfo.relpath}:{qual}"
        self.nested: Dict[str, "FuncInfo"] = {}
        # Calling a generator function only CREATES the generator; its
        # body runs at iteration time (usually on another thread/stack),
        # so blocks/acquires must not propagate to the call site.
        self.is_generator = any(
            isinstance(n, (ast.Yield, ast.YieldFrom))
            for n in _walk_skipping_defs(node)
        )
        # Filled by the analysis passes:
        self.calls: List[Tuple[ast.Call, "FuncInfo"]] = []
        self.direct_blocks: List[Tuple[ast.Call, Block]] = []
        self.direct_acquires: List[Tuple[LockInfo, ast.AST]] = []
        self.blocks: Dict[Tuple[str, frozenset], Block] = {}
        self.acquires: Dict[str, Tuple[str, Tuple[str, int]]] = {}  # lockkey -> (chain, site)

    @property
    def name(self) -> str:
        return self.node.name


class ModuleInfo:
    def __init__(self, module: Module) -> None:
        self.module = module
        self.relpath = module.relpath
        self.dotted = _dotted_module_name(module.relpath)
        self.package = (
            self.dotted
            if module.relpath.endswith("__init__.py")
            else ".".join(self.dotted.split(".")[:-1])
        )
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}  # name -> (module, attr|None)
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.aliases: Dict[str, str] = {}            # g = f (module level)
        self.var_type_exprs: Dict[str, Tuple[Tuple[str, ...], ast.AST]] = {}
        self.var_pending: List[Tuple[str, ast.Call]] = []
        self.var_types: Dict[str, ClassInfo] = {}
        self.var_locks: Dict[str, LockInfo] = {}


def _dotted_module_name(relpath: str) -> str:
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _ann_names(node: Optional[ast.AST]) -> List[str]:
    """Class names named by a type annotation: ``X`` → [X];
    ``Optional[X]`` → [X]; ``Union[X, Y]`` / ``X | Y`` → [X, Y];
    string annotations are parsed.  Unresolvable shapes → []."""
    if node is None:
        return []
    if isinstance(node, ast.Constant):
        if not isinstance(node.value, str):
            return []
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return []
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _ann_names(node.left) + _ann_names(node.right)
    if isinstance(node, ast.Subscript):
        name = dotted(node.value)
        if name and name.split(".")[-1] == "Optional":
            return _ann_names(node.slice)
        if name and name.split(".")[-1] == "Union":
            if isinstance(node.slice, ast.Tuple):
                out: List[str] = []
                for elt in node.slice.elts:
                    out.extend(_ann_names(elt))
                return out
            return _ann_names(node.slice)
        return []
    d = dotted(node)
    if d is None or d == "None":
        return []
    return [d]


class UnionClass:
    """Synthetic class for ``Union[A, B]`` annotations: method lookup
    fans out across members, attribute lookup takes the first hit.  It
    quacks like :class:`ClassInfo` everywhere the resolver cares."""

    def __init__(self, members: List[ClassInfo]) -> None:
        self.members = members
        self.module = members[0].module
        self.name = "|".join(m.name for m in members)
        self.children: List[ClassInfo] = []

    def mro(self) -> List[ClassInfo]:
        out: List[ClassInfo] = []
        for m in self.members:
            out.extend(m.mro())
        return out

    def descendants(self) -> List[ClassInfo]:
        out: List[ClassInfo] = []
        for m in self.members:
            out.extend(m.descendants())
        return out

    def find_method(self, name: str):
        for m in self.members:
            hit = m.find_method(name)
            if hit is not None:
                return hit
        return None

    def find_methods(self, name: str):
        out = []
        seen: Set[int] = set()
        for m in self.members:
            for owner, fn in m.find_methods(name):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    out.append((owner, fn))
        return out

    def attr_lock(self, name: str) -> Optional[LockInfo]:
        for m in self.members:
            lock = m.attr_lock(name)
            if lock is not None:
                return lock
        return None

    def attr_type(self, name: str):
        for m in self.members:
            t = m.attr_type(name)
            if t is not None:
                return t
        return None


def _lock_factory_of(call: ast.Call) -> Optional[str]:
    name = dotted(call.func)
    if not name:
        return None
    parts = name.split(".")
    if parts[-1] in _LOCK_FACTORIES and (len(parts) == 1 or parts[-2] == "threading"):
        return parts[-1]
    return None


class Program:
    """The linked whole-program view.  Build once, query findings/graph."""

    def __init__(self, modules: Sequence[Module]) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.locks: Dict[str, LockInfo] = {}
        self.edges: Dict[Tuple[str, str], Edge] = {}
        self._findings: List[Finding] = []
        self._reported: Set[Tuple[str, int, str, frozenset]] = set()
        for m in modules:
            mi = ModuleInfo(m)
            self.modules[mi.relpath] = mi
            self.by_dotted[mi.dotted] = mi
        for mi in self.modules.values():
            self._index_module(mi)
        self._link()
        for fi in list(self.funcs.values()):
            self._collect_direct(fi)
        self._fixpoint()
        for fi in self.funcs.values():
            self._emit(fi)
        self._emit_cycles()
        self._findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    @classmethod
    def from_paths(cls, paths: Iterable[Path], root: Path) -> "Program":
        modules = []
        for path in collect_files(paths, root):
            try:
                modules.append(load_module(path, root))
            except (SyntaxError, UnicodeDecodeError):
                continue
        return cls(modules)

    # ------------------------------------------------------------------
    # Pass 1: per-module indexing
    # ------------------------------------------------------------------

    def _index_module(self, mi: ModuleInfo) -> None:
        tree = mi.module.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    mi.imports[bound] = (target, None)
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_import_base(mi, node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    mi.imports[a.asname or a.name] = (base, a.name)
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(mi, stmt, None, stmt.name)
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(mi, stmt)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._index_module_assign(mi, stmt)

    def _resolve_import_base(self, mi: ModuleInfo, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        pkg_parts = mi.package.split(".") if mi.package else []
        up = node.level - 1
        if up > len(pkg_parts):
            return None
        base_parts = pkg_parts[: len(pkg_parts) - up]
        if node.module:
            base_parts.extend(node.module.split("."))
        return ".".join(base_parts)

    def _index_function(
        self,
        mi: ModuleInfo,
        node: ast.FunctionDef,
        cls: Optional[ClassInfo],
        qual: str,
    ) -> FuncInfo:
        fi = FuncInfo(mi, node, cls, qual)
        self.funcs[fi.key] = fi
        if cls is None and "." not in qual:
            mi.functions[node.name] = fi
        for stmt in node.body:
            self._index_nested(mi, stmt, fi, cls, qual)
        return fi

    def _index_nested(self, mi, stmt, parent: FuncInfo, cls, qual) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child = self._index_function(mi, stmt, cls, f"{qual}.{stmt.name}")
            parent.nested[stmt.name] = child
            return
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, (ast.stmt,)):
                self._index_nested(mi, sub, parent, cls, qual)

    def _index_class(self, mi: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(mi, node)
        mi.classes[node.name] = ci
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = stmt
                self._index_function(mi, stmt, ci, f"{node.name}.{stmt.name}")
                self._scan_self_assigns(mi, ci, stmt)

    def _scan_self_assigns(self, mi: ModuleInfo, ci: ClassInfo, fn: ast.FunctionDef) -> None:
        params = _param_annotations(fn)
        for node in ast.walk(fn):
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            attr = target.attr
            if isinstance(value, ast.Call):
                factory = _lock_factory_of(value)
                if factory:
                    self._register_lock(
                        mi, f"{ci.name}.{attr}", factory, value, ci, attr
                    )
                    continue
                # Constructor call or factory-method call; the link-phase
                # fixpoint resolves either (the latter via the callee's
                # return annotation).
                ci.attr_pending.append((attr, value, fn))
            elif isinstance(value, ast.Name) and value.id in params:
                names = params[value.id]
                if names:
                    ci.attr_type_exprs.setdefault(attr, (tuple(names), value))
            elif isinstance(value, ast.BoolOp):
                # `self.x = param or Default()` — try each operand.
                for operand in value.values:
                    if isinstance(operand, ast.Call) and not _lock_factory_of(operand):
                        ci.attr_pending.append((attr, operand, fn))
                        break
                    if isinstance(operand, ast.Name) and params.get(operand.id):
                        ci.attr_type_exprs.setdefault(
                            attr, (tuple(params[operand.id]), operand)
                        )
                        break
            elif isinstance(value, ast.IfExp):
                # `self._table = backend.table("jobs") if backend else None`
                for branch in (value.body, value.orelse):
                    if isinstance(branch, ast.Call) and not _lock_factory_of(branch):
                        ci.attr_pending.append((attr, branch, fn))
                        break

    def _index_module_assign(self, mi: ModuleInfo, stmt) -> None:
        if isinstance(stmt, ast.Assign):
            if len(stmt.targets) != 1 or not isinstance(stmt.targets[0], ast.Name):
                return
            name, value = stmt.targets[0].id, stmt.value
        else:
            if not isinstance(stmt.target, ast.Name):
                return
            name, value = stmt.target.id, stmt.value
            # `_active: Optional[FaultInjector] = None` — the annotation
            # types the variable even when the initial value doesn't.
            names = _ann_names(stmt.annotation)
            if names:
                mi.var_type_exprs.setdefault(name, (tuple(names), stmt))
            if value is None:
                return
        if isinstance(value, ast.Call):
            factory = _lock_factory_of(value)
            if factory:
                lock = LockInfo(
                    key=f"{mi.relpath}:<module>.{name}", kind=factory,
                    sites=[(mi.relpath, value.lineno)],
                )
                self.locks[lock.key] = lock
                mi.var_locks[name] = lock
                return
            mi.var_pending.append((name, value))
        elif isinstance(value, ast.Name):
            mi.aliases[name] = value.id

    def _register_lock(
        self,
        mi: ModuleInfo,
        owner: str,
        factory: str,
        call: ast.Call,
        ci: Optional[ClassInfo],
        attr: Optional[str],
    ) -> None:
        key = f"{mi.relpath}:{owner}"
        lock = self.locks.get(key)
        if lock is None:
            lock = LockInfo(key=key, kind=factory)
            self.locks[key] = lock
        lock.sites.append((mi.relpath, call.lineno))
        if ci is not None and attr is not None:
            ci.attr_locks[attr] = lock
            if factory == "Condition" and call.args:
                wrapped = dotted(call.args[0])
                if wrapped and wrapped.startswith("self."):
                    ci._cond_aliases[attr] = wrapped.split(".", 1)[1]

    # ------------------------------------------------------------------
    # Pass 2: linking (bases, attr types, condition aliases)
    # ------------------------------------------------------------------

    def _link(self) -> None:
        for mi in self.modules.values():
            for ci in mi.classes.values():
                for base in ci.base_exprs:
                    resolved = self._resolve_class_expr(mi, base)
                    if resolved is not None and resolved is not ci:
                        ci.bases.append(resolved)
                        resolved.children.append(ci)
        # Type-inference fixpoint: constructor exprs, annotated params,
        # and factory-method calls (via return annotations) feed each
        # other — `self._table = backend.table(ns)` needs `backend`'s
        # type before `.table`'s `-> KVTable` can type `_table`.
        changed = True
        while changed:
            changed = False
            for mi in self.modules.values():
                for name, (exprs, _site) in list(mi.var_type_exprs.items()):
                    if name in mi.var_types:
                        continue
                    ci = self._resolve_names(mi, exprs)
                    if ci is not None:
                        mi.var_types[name] = ci
                        changed = True
                for name, call in list(mi.var_pending):
                    if name in mi.var_types:
                        continue
                    ci = self._infer_call_type(mi, None, None, call)
                    if ci is not None:
                        mi.var_types[name] = ci
                        changed = True
                for owner in mi.classes.values():
                    for attr, (exprs, _site) in list(owner.attr_type_exprs.items()):
                        if attr in owner.attr_types:
                            continue
                        resolved = self._resolve_names(mi, exprs)
                        if resolved is not None:
                            owner.attr_types[attr] = resolved
                            changed = True
                    for attr, call, fn in list(owner.attr_pending):
                        if attr in owner.attr_types:
                            continue
                        resolved = self._infer_call_type(mi, owner, fn, call)
                        if resolved is not None:
                            owner.attr_types[attr] = resolved
                            changed = True
        for mi in self.modules.values():
            for ci in mi.classes.values():
                for cv_attr, wrapped_attr in ci._cond_aliases.items():
                    cv = ci.attr_locks.get(cv_attr)
                    wrapped = ci.attr_lock(wrapped_attr)
                    if cv is not None and wrapped is not None and cv is not wrapped:
                        cv.wraps = wrapped

    def _infer_call_type(
        self,
        mi: ModuleInfo,
        cls_ctx: Optional[ClassInfo],
        fn: Optional[ast.FunctionDef],
        call: ast.Call,
    ) -> Optional[ClassInfo]:
        """Type of a call expression: a project-class constructor, or a
        project function/method whose return annotation names a class."""
        callee = dotted(call.func)
        if callee is None:
            return None
        ci = self._resolve_class_expr(mi, callee)
        if ci is not None:
            return ci
        target = self._resolve_func_dotted(mi, cls_ctx, fn, callee.split("."))
        if target is None or target.node.returns is None:
            return None
        return self._resolve_names(
            target.module, _ann_names(target.node.returns)
        )

    def _resolve_names(self, mi: ModuleInfo, names: Iterable[str]):
        """Resolve one-or-more dotted class names; >1 hit → UnionClass."""
        resolved: List[ClassInfo] = []
        seen: Set[int] = set()
        for n in names:
            ci = self._resolve_class_expr(mi, n)
            if ci is not None and id(ci) not in seen:
                seen.add(id(ci))
                resolved.append(ci)
        if not resolved:
            return None
        if len(resolved) == 1:
            return resolved[0]
        return UnionClass(resolved)

    def _resolve_func_dotted(
        self,
        mi: ModuleInfo,
        cls_ctx: Optional[ClassInfo],
        fn: Optional[ast.FunctionDef],
        parts: List[str],
    ) -> Optional[FuncInfo]:
        """Best-effort dotted-callee resolution for type inference (no
        local FuncInfo context; a small param/constructor scan stands in
        for local variable types)."""
        head, rest = parts[0], parts[1:]
        if head in ("self", "cls") and cls_ctx is not None:
            ctx: Optional[ClassInfo] = cls_ctx
            for attr in rest[:-1]:
                ctx = ctx.attr_type(attr) if ctx is not None else None
            if ctx is not None and rest:
                hit = ctx.find_method(rest[-1])
                if hit is not None:
                    return self._method_func(hit[0], hit[1])
            return None
        local_ci: Optional[ClassInfo] = None
        if fn is not None:
            local_ci = self._quick_local_type(mi, fn, head)
        if local_ci is None and head in mi.var_types:
            local_ci = mi.var_types[head]
        if local_ci is None and head in mi.imports:
            local_ci = self._var_type_from_import(mi.imports[head])
        if local_ci is not None:
            ctx = local_ci
            for attr in rest[:-1]:
                ctx = ctx.attr_type(attr) if ctx is not None else None
            if ctx is not None and rest:
                hit = ctx.find_method(rest[-1])
                if hit is not None:
                    return self._method_func(hit[0], hit[1])
            return None
        if not rest:
            if head in mi.functions:
                return mi.functions[head]
            imp = mi.imports.get(head)
            if imp:
                return self._func_from_import(imp)
            return None
        imp = mi.imports.get(head)
        if imp:
            target = self._module_from_import(imp)
            if target is not None and len(rest) == 1:
                return target.functions.get(rest[0])
        return None

    def _quick_local_type(
        self, mi: ModuleInfo, fn: ast.FunctionDef, name: str
    ) -> Optional[ClassInfo]:
        """Type of local ``name`` inside ``fn``: annotated parameter or a
        direct constructor assignment (last one wins)."""
        found: Optional[ClassInfo] = None
        names = _param_annotations(fn).get(name) or []
        if names:
            found = self._resolve_names(mi, names)
        for node in _walk_skipping_defs(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Call)
            ):
                callee = dotted(node.value.func)
                if callee:
                    ci = self._resolve_class_expr(mi, callee)
                    if ci is not None:
                        found = ci
        return found

    def _var_type_from_import(self, imp: Tuple[str, Optional[str]]) -> Optional[ClassInfo]:
        """``from x import VAR [as alias]`` where VAR is a typed
        module-level variable (e.g. ``default_registry``)."""
        mod, attr = imp
        if attr is None:
            return None
        target = self.by_dotted.get(mod)
        if target is None:
            return None
        if attr in target.var_types:
            return target.var_types[attr]
        inner = target.imports.get(attr)
        if inner:
            return self._var_type_from_import(inner)
        return None

    def _resolve_class_expr(self, mi: ModuleInfo, expr: str) -> Optional[ClassInfo]:
        parts = expr.split(".")
        head, rest = parts[0], parts[1:]
        seen = set()
        while head in mi.aliases and head not in seen:
            seen.add(head)
            head = mi.aliases[head]
        if not rest:
            if head in mi.classes:
                return mi.classes[head]
            imp = mi.imports.get(head)
            if imp:
                return self._class_from_import(imp)
            return None
        imp = mi.imports.get(head)
        if imp is None:
            return None
        target = self._module_from_import(imp)
        if target is None or len(rest) != 1:
            return None
        return target.classes.get(rest[0])

    def _module_from_import(self, imp: Tuple[str, Optional[str]]) -> Optional[ModuleInfo]:
        mod, attr = imp
        if attr is None:
            return self.by_dotted.get(mod)
        return self.by_dotted.get(f"{mod}.{attr}")

    def _class_from_import(self, imp: Tuple[str, Optional[str]]) -> Optional[ClassInfo]:
        mod, attr = imp
        if attr is None:
            return None
        target = self.by_dotted.get(mod)
        if target is not None and attr in target.classes:
            return target.classes[attr]
        # `from pkg import name` where name is re-exported by __init__:
        # chase one level of the package's own imports.
        if target is not None:
            inner = target.imports.get(attr)
            if inner:
                return self._class_from_import(inner)
        return None

    def _func_from_import(self, imp: Tuple[str, Optional[str]]) -> Optional[FuncInfo]:
        mod, attr = imp
        if attr is None:
            return None
        target = self.by_dotted.get(mod)
        if target is None:
            return None
        if attr in target.functions:
            return target.functions[attr]
        alias = target.aliases.get(attr)
        if alias and alias in target.functions:
            return target.functions[alias]
        inner = target.imports.get(attr)
        if inner:
            return self._func_from_import(inner)
        return None

    # ------------------------------------------------------------------
    # Local resolution helpers
    # ------------------------------------------------------------------

    def _local_types(self, fi: FuncInfo) -> Tuple[Dict[str, ClassInfo], Dict[str, LockInfo]]:
        """Forward scan: local-variable class types and local locks, plus
        annotated parameters."""
        types: Dict[str, ClassInfo] = {}
        locks: Dict[str, LockInfo] = {}
        for name, names in _param_annotations(fi.node).items():
            if names:
                ci = self._resolve_names(fi.module, names)
                if ci is not None:
                    types[name] = ci
        for node in _walk_skipping_defs(fi.node):
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not isinstance(target, ast.Name):
                continue
            if isinstance(value, ast.Call):
                factory = _lock_factory_of(value)
                if factory:
                    key = f"{fi.module.relpath}:{fi.qual}.<local>{target.id}"
                    lock = self.locks.get(key)
                    if lock is None:
                        lock = LockInfo(key=key, kind=factory)
                        self.locks[key] = lock
                    lock.sites.append((fi.module.relpath, value.lineno))
                    locks[target.id] = lock
                    continue
                ci = self._class_of_call(fi, value)
                if ci is not None:
                    types[target.id] = ci
            elif isinstance(value, ast.Attribute):
                resolved = self._resolve_attr_chain(fi, value, types, locks)
                if isinstance(resolved, (ClassInfo, UnionClass)):
                    types[target.id] = resolved
                elif isinstance(resolved, LockInfo):
                    locks[target.id] = resolved
            elif isinstance(value, ast.Name):
                # `inj = _active` — copy the type of a local, module, or
                # imported-module variable.
                src = value.id
                mi = fi.module
                if src in types:
                    types[target.id] = types[src]
                elif src in locks:
                    locks[target.id] = locks[src]
                elif src in mi.var_types:
                    types[target.id] = mi.var_types[src]
                elif src in mi.var_locks:
                    locks[target.id] = mi.var_locks[src]
                elif src in mi.imports:
                    ci = self._var_type_from_import(mi.imports[src])
                    if ci is not None:
                        types[target.id] = ci
        return types, locks

    def _class_of_call(self, fi: FuncInfo, call: ast.Call) -> Optional[ClassInfo]:
        callee = dotted(call.func)
        if callee is None:
            return None
        return self._resolve_class_expr(fi.module, callee)

    def _resolve_attr_chain(self, fi, node: ast.Attribute, types, locks):
        """Resolve ``self.a.b`` / ``x.a`` to a ClassInfo or LockInfo."""
        chain: List[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            chain.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        chain.reverse()
        base = cur.id
        if base in ("self", "cls") and fi.cls is not None:
            ctx: Optional[ClassInfo] = fi.cls
        elif base in types:
            ctx = types[base]
        elif base in locks and not chain:
            return locks[base]
        else:
            mi = fi.module
            if base in mi.var_locks and not chain:
                return mi.var_locks[base]
            if base in mi.var_types:
                ctx = mi.var_types[base]
            elif base in mi.imports:
                target = self._module_from_import(mi.imports[base])
                if target is None:
                    ctx = self._var_type_from_import(mi.imports[base])
                    if ctx is None:
                        return None
                elif not chain:
                    return None
                else:
                    head = chain.pop(0)
                    if head in target.var_locks and not chain:
                        return target.var_locks[head]
                    ctx = target.var_types.get(head)
                    if ctx is None and not chain and head in target.classes:
                        return target.classes[head]
            else:
                return None
        for i, attr in enumerate(chain):
            if ctx is None:
                return None
            last = i == len(chain) - 1
            if last:
                lock = ctx.attr_lock(attr)
                if lock is not None:
                    return lock
                return ctx.attr_type(attr)
            ctx = ctx.attr_type(attr)
        return ctx

    def resolve_lock_expr(self, fi: FuncInfo, expr: ast.AST, types, locks) -> Optional[LockInfo]:
        if isinstance(expr, ast.Name):
            if expr.id in locks:
                return locks[expr.id]
            return fi.module.var_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            resolved = self._resolve_attr_chain(fi, expr, types, locks)
            if isinstance(resolved, LockInfo):
                return resolved
        return None

    def resolve_calls(self, fi: FuncInfo, call: ast.Call, types, locks) -> List[FuncInfo]:
        """Every project function this call may reach (virtual dispatch:
        a method resolved on a base type fans out to its overrides)."""
        func = call.func
        mi = fi.module

        def one(x: Optional[FuncInfo]) -> List[FuncInfo]:
            return [x] if x is not None else []

        def methods_of(ci: ClassInfo, name: str) -> List[FuncInfo]:
            out = []
            for owner, fn in ci.find_methods(name):
                target = self._method_func(owner, fn)
                if target is not None:
                    out.append(target)
            return out

        if isinstance(func, ast.Name):
            name = func.id
            seen = set()
            while name in mi.aliases and name not in seen:
                seen.add(name)
                name = mi.aliases[name]
            cur: Optional[FuncInfo] = fi
            while cur is not None:
                if name in cur.nested:
                    return [cur.nested[name]]
                cur = self._parent_func(cur)
            if name in mi.functions:
                return [mi.functions[name]]
            if name in mi.classes:
                return one(self._init_of(mi.classes[name]))
            if name in types:
                return one(self._init_of(types[name]))
            imp = mi.imports.get(name)
            if imp:
                target = self._func_from_import(imp)
                if target is not None:
                    return [target]
                ci = self._class_from_import(imp)
                if ci is not None:
                    return one(self._init_of(ci))
            return []
        if not isinstance(func, ast.Attribute):
            return []
        # super().m()
        if (
            isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Name)
            and func.value.func.id == "super"
            and fi.cls is not None
        ):
            for base in fi.cls.bases:
                hit = base.find_method(func.attr)
                if hit is not None:
                    return one(self._method_func(hit[0], hit[1]))
            return []
        method = func.attr
        recv = func.value
        if isinstance(recv, ast.Name):
            base = recv.id
            if base in ("self", "cls") and fi.cls is not None:
                # `self` may be any subclass of the enclosing class.
                hits = methods_of(fi.cls, method)
                if hits:
                    return hits
            if base in types:
                return methods_of(types[base], method)
            if base in locks:
                return []   # lock method (.acquire/.release/.wait/...)
            imp = mi.imports.get(base)
            if imp is not None:
                target = self._module_from_import(imp)
                if target is not None:
                    if method in target.functions:
                        return [target.functions[method]]
                    if method in target.classes:
                        return one(self._init_of(target.classes[method]))
                    alias = target.aliases.get(method)
                    if alias and alias in target.functions:
                        return [target.functions[alias]]
                    inner = target.imports.get(method)
                    if inner:
                        hit2 = self._func_from_import(inner)
                        if hit2 is not None:
                            return [hit2]
                        ci = self._class_from_import(inner)
                        if ci is not None:
                            return one(self._init_of(ci))
                    return []
                ci = self._class_from_import(imp)
                if ci is not None:
                    return methods_of(ci, method)
                ci = self._var_type_from_import(imp)
                if ci is not None:
                    return methods_of(ci, method)
                return []
            if base in mi.var_types:
                return methods_of(mi.var_types[base], method)
            if base in mi.classes:
                return methods_of(mi.classes[base], method)
            return []
        if isinstance(recv, ast.Attribute):
            ctx = self._resolve_attr_chain(fi, recv, types, locks)
            if isinstance(ctx, (ClassInfo, UnionClass)):
                return methods_of(ctx, method)
            if isinstance(ctx, LockInfo):
                return []
        return []

    def _parent_func(self, fi: FuncInfo) -> Optional[FuncInfo]:
        if "." not in fi.qual:
            return None
        parent_qual = fi.qual.rsplit(".", 1)[0]
        return self.funcs.get(f"{fi.module.relpath}:{parent_qual}")

    def _init_of(self, ci: ClassInfo) -> Optional[FuncInfo]:
        hit = ci.find_method("__init__")
        if hit is None:
            return None
        return self._method_func(hit[0], hit[1])

    def _method_func(self, ci: ClassInfo, fn: ast.FunctionDef) -> Optional[FuncInfo]:
        return self.funcs.get(f"{ci.module.relpath}:{ci.name}.{fn.name}")

    # ------------------------------------------------------------------
    # Blocking-operation classification (for calls that do NOT resolve
    # to a project function — project calls carry their own summaries)
    # ------------------------------------------------------------------

    def classify_blocking(self, fi: FuncInfo, call: ast.Call, types, locks) -> Optional[Block]:
        name = dotted(call.func) or ""
        leaf = name.split(".")[-1] if name else (
            call.func.attr if isinstance(call.func, ast.Attribute) else ""
        )
        kwargs = {k.arg for k in call.keywords}
        npos = len(call.args)

        def bounded_by_timeout() -> bool:
            if "timeout" in kwargs:
                kw = next(k for k in call.keywords if k.arg == "timeout")
                return not (isinstance(kw.value, ast.Constant) and kw.value.value is None)
            return False

        if leaf in _NET_LEAVES:
            return Block(f"{leaf}() [network I/O]", frozenset(), f"{leaf}()")
        if name in _SUBPROCESS_CALLS:
            if bounded_by_timeout():
                return None   # bounded build/tool invocation
            return Block(f"{name}() [subprocess]", frozenset(), f"{name}()")
        if leaf == "communicate":
            if bounded_by_timeout():
                return None
            return Block("Popen.communicate() [subprocess]", frozenset(), "communicate()")
        if leaf in ("serve_forever", "handle_request"):
            return Block(f"{leaf}() [server loop]", frozenset(), f"{leaf}()")
        if leaf == "select" and name.startswith("select."):
            if npos < 4:
                return Block("select.select() without timeout", frozenset(), "select.select()")
            return None
        if leaf in _SOCKET_LEAVES:
            if any(name.startswith(p) for p in _NOT_SOCKET_PREFIXES):
                return None
            return Block(f".{leaf}() [socket I/O]", frozenset(), f".{leaf}()")
        if leaf == "get" and npos == 0 and not kwargs:
            return Block("queue .get() without timeout", frozenset(), ".get()")
        if leaf == "join" and npos == 0:
            if bounded_by_timeout():
                return None
            if not kwargs:
                return Block(".join() without timeout", frozenset(), ".join()")
            return None
        if leaf == "result" and npos == 0 and not bounded_by_timeout() and "timeout" not in kwargs:
            if isinstance(call.func, ast.Attribute):
                return Block("Future.result() without timeout", frozenset(), ".result()")
            return None
        if leaf == "wait":
            if bounded_by_timeout():
                return None
            if npos:
                first = call.args[0]
                if not (isinstance(first, ast.Constant) and first.value is None):
                    return None  # wait(secs) is bounded
            if not isinstance(call.func, ast.Attribute):
                return None
            lock = self.resolve_lock_expr(fi, call.func.value, types, locks)
            if lock is not None:
                # Condition.wait releases its own lock while blocked.
                return Block(
                    ".wait() without timeout [condition]",
                    frozenset({lock.base().key}),
                    ".wait()",
                )
            return Block(".wait() without timeout", frozenset(), ".wait()")
        return None

    # ------------------------------------------------------------------
    # Pass 3a: per-function direct facts (calls, blocking ops, acquires)
    # ------------------------------------------------------------------

    def _collect_direct(self, fi: FuncInfo) -> None:
        types, locks = self._local_types(fi)
        fi._types, fi._locks = types, locks  # cached for the emit pass
        for call in _calls_in(fi.node):
            targets = self.resolve_calls(fi, call, types, locks)
            for target in targets:
                if target is not fi:
                    fi.calls.append((call, target))
            # retry_call resolves to the project's own retry loop, but its
            # payload is a dynamic callable (the transport) the resolver
            # cannot see — the call is still network-blocking.  Classify
            # it (and any other *resolved* net leaf) in addition to
            # following its body for lock edges.
            name = dotted(call.func) or ""
            if not targets or (name.split(".")[-1] in _NET_LEAVES):
                block = self.classify_blocking(fi, call, types, locks)
                if block is not None:
                    fi.direct_blocks.append((call, block))
        for node in _walk_skipping_defs(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lock = self.resolve_lock_expr(fi, item.context_expr, types, locks)
                    if lock is not None:
                        fi.direct_acquires.append((lock, item.context_expr))
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    lock = self.resolve_lock_expr(fi, node.func.value, types, locks)
                    if lock is not None:
                        fi.direct_acquires.append((lock, node))

    # ------------------------------------------------------------------
    # Pass 3b: transitive summaries (fixpoint over the call graph)
    # ------------------------------------------------------------------

    def _fixpoint(self) -> None:
        for fi in self.funcs.values():
            for call, block in fi.direct_blocks:
                fi.blocks.setdefault((block.desc, block.releases), block)
            for lock, node in fi.direct_acquires:
                base = lock.base()
                fi.acquires.setdefault(
                    base.key,
                    (f"{fi.qual}", (fi.module.relpath, getattr(node, "lineno", 1))),
                )
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                for _call, target in fi.calls:
                    if target.is_generator:
                        continue
                    for (desc, releases), block in target.blocks.items():
                        key = (desc, releases)
                        if key not in fi.blocks:
                            fi.blocks[key] = Block(
                                desc, releases, f"{target.qual} -> {block.chain}"
                            )
                            changed = True
                    for lockkey, (chain, site) in target.acquires.items():
                        if lockkey not in fi.acquires:
                            chained = chain if chain.startswith(target.qual) else f"{target.qual} -> {chain}"
                            fi.acquires[lockkey] = (chained, site)
                            changed = True

    # ------------------------------------------------------------------
    # Pass 3c: region walk — findings + lock-order edges
    # ------------------------------------------------------------------

    def _emit(self, fi: FuncInfo) -> None:
        self._walk_body(fi, list(fi.node.body), [])

    def _walk_body(self, fi: FuncInfo, body: List[ast.stmt], held) -> None:
        i = 0
        while i < len(body):
            stmt = body[i]
            acquired = self._manual_acquire(fi, stmt)
            if acquired is not None:
                lock, node = acquired
                self._note_acquire(fi, lock, node, held)
                rest = body[i + 1 :]
                cut = len(rest)
                for j, s in enumerate(rest):
                    if self._manual_release(fi, s) is lock:
                        cut = j
                        break
                self._walk_body(fi, rest[:cut], held + [(lock, node)])
                i += 1 + cut
                continue
            self._walk_stmt(fi, stmt, held)
            i += 1

    def _manual_acquire(self, fi: FuncInfo, stmt: ast.stmt):
        call = None
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        if (
            call is not None
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            lock = self.resolve_lock_expr(fi, call.func.value, fi._types, fi._locks)
            if lock is not None:
                return lock, call
        return None

    def _manual_release(self, fi: FuncInfo, stmt: ast.stmt):
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "release"
        ):
            return self.resolve_lock_expr(fi, stmt.value.func.value, fi._types, fi._locks)
        return None

    def _walk_stmt(self, fi: FuncInfo, stmt: ast.stmt, held) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.With):
            entered = list(held)
            for item in stmt.items:
                self._scan_expr(fi, item.context_expr, entered)
                lock = self.resolve_lock_expr(
                    fi, item.context_expr, fi._types, fi._locks
                )
                if lock is not None:
                    self._note_acquire(fi, lock, item.context_expr, entered)
                    entered = entered + [(lock, item.context_expr)]
            self._walk_body(fi, list(stmt.body), entered)
            return
        for expr in _stmt_exprs(stmt):
            self._scan_expr(fi, expr, held)
        for sub_body in _stmt_bodies(stmt):
            self._walk_body(fi, list(sub_body), held)

    def _note_acquire(self, fi: FuncInfo, lock: LockInfo, node: ast.AST, held) -> None:
        base = lock.base()
        for h, _n in held:
            self._add_edge(
                h.base().key, base.key, fi.module.relpath,
                getattr(node, "lineno", 1), fi.qual,
            )

    def _add_edge(self, src: str, dst: str, relpath: str, line: int, chain: str) -> None:
        mi = self.modules.get(relpath)
        if mi is not None and mi.module.suppressed(RULE_ORDER, line):
            return
        self.edges.setdefault((src, dst), Edge(src, dst, relpath, line, chain))

    def _scan_expr(self, fi: FuncInfo, expr: ast.AST, held) -> None:
        for call in _calls_in_expr(expr):
            targets = self.resolve_calls(fi, call, fi._types, fi._locks)
            if targets:
                if not held:
                    continue
                for target in targets:
                    if target is fi or target.is_generator:
                        continue
                    for (desc, releases), block in target.blocks.items():
                        self._report_blocking(fi, call, held, Block(
                            desc, releases, f"{target.qual} -> {block.chain}"
                        ))
                    for lockkey, (chain, _site) in target.acquires.items():
                        for h, _n in held:
                            # Self-edges (same lock class re-acquired) stay
                            # in the graph for witness parity; DF009 skips
                            # them when hunting cycles.
                            self._add_edge(
                                h.base().key, lockkey, fi.module.relpath,
                                call.lineno, f"{fi.qual} -> {chain}",
                            )
            if held and (not targets or (dotted(call.func) or "").split(".")[-1] in _NET_LEAVES):
                block = self.classify_blocking(fi, call, fi._types, fi._locks)
                if block is not None:
                    self._report_blocking(fi, call, held, block)

    def _report_blocking(self, fi: FuncInfo, call: ast.Call, held, block: Block) -> None:
        module = fi.module.module
        if module.suppressed(RULE_BLOCKING, call.lineno):
            return
        blocked = [
            h for h, _n in held if h.base().key not in block.releases
        ]
        if not blocked:
            return
        dedupe = (
            fi.module.relpath, call.lineno, block.desc,
            frozenset(h.base().key for h in blocked),
        )
        if dedupe in self._reported:
            return
        self._reported.add(dedupe)
        names = ", ".join(sorted({h.base().key.split(":", 1)[1] for h in blocked}))
        self._findings.append(
            Finding(
                rule=RULE_BLOCKING,
                path=fi.module.relpath,
                line=call.lineno,
                col=call.col_offset + 1,
                message=(
                    f"{block.desc} while holding {names} "
                    f"(chain: {fi.qual} -> {block.chain})"
                ),
                qual=module.qualname(call),
            )
        )

    # ------------------------------------------------------------------
    # DF009 — cycles in the lock-order graph
    # ------------------------------------------------------------------

    def _emit_cycles(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (src, dst) in self.edges:
            if src != dst:
                adj.setdefault(src, set()).add(dst)
                adj.setdefault(dst, set())
        for scc in _tarjan(adj):
            if len(scc) < 2:
                continue
            cycle = _concrete_cycle(adj, scc)
            edge_list = [
                self.edges[(cycle[i], cycle[(i + 1) % len(cycle)])]
                for i in range(len(cycle))
                if (cycle[i], cycle[(i + 1) % len(cycle)]) in self.edges
            ]
            if not edge_list:
                continue
            anchor = min(edge_list, key=lambda e: (e.relpath, e.line))
            detail = "; ".join(
                f"{e.src.split(':', 1)[1]} -> {e.dst.split(':', 1)[1]} "
                f"({e.relpath}:{e.line})"
                for e in edge_list
            )
            mi = self.modules.get(anchor.relpath)
            qual = "<module>"
            if mi is not None:
                fn = self.funcs.get(f"{anchor.relpath}:{anchor.chain.split(' ->')[0]}")
                qual = fn.qual if fn is not None else anchor.chain.split(" ->")[0]
            self._findings.append(
                Finding(
                    rule=RULE_ORDER,
                    path=anchor.relpath,
                    line=anchor.line,
                    col=1,
                    message=f"lock-order inversion: {detail}",
                    qual=qual,
                )
            )

    # ------------------------------------------------------------------
    # Public surface
    # ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        return list(self._findings)

    def creation_site_index(self) -> Dict[Tuple[str, int], str]:
        """(relpath, lineno) of every ``threading.X()`` creation call →
        lock-class key.  The dynamic witness maps runtime locks through
        this; an unknown site there means the static pass missed a lock."""
        out: Dict[Tuple[str, int], str] = {}
        for lock in self.locks.values():
            for site in lock.sites:
                out[site] = lock.base().key
        return out

    def edge_keys(self) -> Set[Tuple[str, str]]:
        return set(self.edges)

    def lock_graph_dot(self) -> str:
        lines = ["digraph lock_order {", '  rankdir="LR";']
        nodes = sorted({k for e in self.edges for k in (e[0], e[1])})
        for n in nodes:
            label = n.split(":", 1)[1]
            lines.append(f'  "{n}" [label="{label}"];')
        for (src, dst), e in sorted(self.edges.items()):
            lines.append(f'  "{src}" -> "{dst}" [label="{e.relpath}:{e.line}"];')
        lines.append("}")
        return "\n".join(lines) + "\n"

    def lock_graph_markdown(self) -> str:
        """The committed lock-hierarchy table (DESIGN.md §16): one row per
        ordering edge, sorted, stable across runs."""
        rows = ["| held lock | then acquires | edge site |", "| --- | --- | --- |"]
        for (src, dst), e in sorted(self.edges.items()):
            rows.append(
                f"| `{src.split(':', 1)[1]}` ({src.split(':', 1)[0]}) "
                f"| `{dst.split(':', 1)[1]}` ({dst.split(':', 1)[0]}) "
                f"| {e.relpath}:{e.line} |"
            )
        return "\n".join(rows) + "\n"


# ---------------------------------------------------------------------------
# AST traversal helpers
# ---------------------------------------------------------------------------


def _param_annotations(fn: ast.FunctionDef) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    args = fn.args
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        out[a.arg] = _ann_names(a.annotation) if a.annotation else []
    return out


def _walk_skipping_defs(fn: ast.FunctionDef):
    """Every node inside ``fn`` but not inside a nested def/class/lambda."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _calls_in(fn: ast.FunctionDef):
    for node in _walk_skipping_defs(fn):
        if isinstance(node, ast.Call):
            yield node


def _calls_in_expr(expr: ast.AST):
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _stmt_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """Expressions evaluated by ``stmt`` itself (not its nested bodies)."""
    out: List[ast.AST] = []
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)
        elif isinstance(value, list):
            out.extend(v for v in value if isinstance(v, ast.expr))
    return out


def _stmt_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out: List[List[ast.stmt]] = []
    for name in ("body", "orelse", "finalbody"):
        block = getattr(stmt, name, None)
        if block:
            out.append(block)
    for handler in getattr(stmt, "handlers", []) or []:
        out.append(handler.body)
    return out


def _tarjan(adj: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # Iterative Tarjan (the lock graph is small, but recursion depth
        # should not depend on it).
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return sccs


def _concrete_cycle(adj: Dict[str, Set[str]], scc: List[str]) -> List[str]:
    """A simple cycle inside ``scc`` for the report (DFS back to start)."""
    members = set(scc)
    start = sorted(scc)[0]
    path = [start]
    seen = {start}

    def dfs(v: str) -> Optional[List[str]]:
        for w in sorted(adj.get(v, ())):
            if w == start and len(path) > 1:
                return list(path)
            if w in members and w not in seen:
                seen.add(w)
                path.append(w)
                hit = dfs(w)
                if hit is not None:
                    return hit
                path.pop()
                seen.discard(w)
        return None

    return dfs(start) or [start]


# ---------------------------------------------------------------------------
# Runner integration
# ---------------------------------------------------------------------------


def witness_gaps(
    program: Program,
    dynamic_edges: Dict[Tuple[Tuple[str, int], Tuple[str, int]], str],
    static_edges: Optional[Set[Tuple[str, str]]] = None,
) -> List[str]:
    """Cross-validate dynamically-observed acquisition-order edges (from
    ``dragonfly2_tpu.utils.dflock``) against the static lock graph.

    Returns human-readable gap descriptions; empty means every runtime
    edge is explained by the static analysis.  A non-empty result is a
    RESOLVER BUG (missed call edge, missed lock creation, missed type),
    not an application bug — the tier-1 cross-check turns it into a test
    failure so the analyzer cannot silently rot.

    ``static_edges`` overrides the program's own edge set (used by the
    mutation-sensitivity tests to prove the check actually bites).

    Self-edges (same lock *class* on both ends) are skipped: two runtime
    instances of one class are indistinguishable statically.
    """
    index = program.creation_site_index()
    edges = program.edge_keys() if static_edges is None else static_edges
    gaps: List[str] = []
    for (src, dst), where in sorted(dynamic_edges.items()):
        src_key = index.get(src)
        dst_key = index.get(dst)
        if src_key is None:
            gaps.append(
                f"unknown lock creation site {src[0]}:{src[1]} "
                f"(held side; first observed by {where})"
            )
            continue
        if dst_key is None:
            gaps.append(
                f"unknown lock creation site {dst[0]}:{dst[1]} "
                f"(acquired side; first observed by {where})"
            )
            continue
        if src_key == dst_key:
            continue
        if (src_key, dst_key) not in edges:
            gaps.append(
                f"dynamic edge {src_key} -> {dst_key} missing from the "
                f"static lock graph (observed: {where}; acquired at "
                f"{dst[0]}:{dst[1]} while holding lock from {src[0]}:{src[1]})"
            )
    return gaps


def run_program(paths: Iterable[Path], root: Path) -> Program:
    return Program.from_paths(paths, root)


def program_findings(paths: Iterable[Path], root: Path) -> List[Finding]:
    return run_program(paths, root).findings()
