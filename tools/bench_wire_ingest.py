"""Wire ingest at north-star rate (VERDICT r3 next-#2 / weak-#2).

Measures the PRODUCTION dataset path end to end — the r3 1B soak fed the
trainer from an in-process thread; this pushes real DFC1 bytes through
the real ``Train`` stream on both transports:

  scheduler side:  DFC1 shard files on disk
  wire:            Train stream, 128 MiB chunks
                   (HTTP rpc/trainer_transport.py; gRPC TrainChunk
                   client-stream, announcer.go:144-237 analog)
  trainer side:    receive_shard_bytes staging → concat_readers decode
                   (memmap) → host→device transfer

Reports MB/s and records/s per stage and end-to-end, against BOTH bars:
the north-star consumption rate (1.3M records/s) and the flagship's
measured train-step consumption (~4.9M records/s/chip, BENCHMARKS.md).
The training kick on stream close is stubbed out — this bench measures
ingest; training throughput has its own benches.

Usage:
  python tools/bench_wire_ingest.py [--gb 2] [--device]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

import numpy as np


def make_shards(directory: str, total_bytes: int, shard_bytes: int) -> list:
    from dragonfly2_tpu.records.columnar import ColumnarWriter
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

    width = len(DOWNLOAD_COLUMNS)
    rows_per_shard = max(shard_bytes // (4 * width), 1)
    n_shards = max(int(np.ceil(total_bytes / (rows_per_shard * 4 * width))), 1)
    rng = np.random.default_rng(0)
    paths = []
    block = rng.random((min(rows_per_shard, 1 << 20), width), np.float32)
    for i in range(n_shards):
        path = os.path.join(directory, f"shard-{i}.dfc")
        with ColumnarWriter(path, DOWNLOAD_COLUMNS) as w:
            left = rows_per_shard
            while left > 0:
                w.append(block[: min(left, len(block))])
                left -= min(left, len(block))
        paths.append(path)
    return paths


def run_transport(kind: str, service, paths, *, ip, hostname):
    """Stream every shard through the given transport; returns
    (seconds, session) with the staged files recorded on the session."""
    if kind == "http":
        from dragonfly2_tpu.rpc.trainer_transport import (
            RemoteTrainer,
            TrainerHTTPServer,
        )

        server = TrainerHTTPServer(service)
        server.serve()
        try:
            client = RemoteTrainer(server.url)
            session = client.open_train_stream(
                ip=ip, hostname=hostname, scheduler_id="bench"
            )
            t0 = time.perf_counter()
            for p in paths:
                session.send_download_shard(p)
            dt = time.perf_counter() - t0
        finally:
            server.stop()
        return dt, session
    else:
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCTrainerClient,
            TrainerGRPCServer,
        )

        server = TrainerGRPCServer(service)
        server.serve()
        try:
            client = GRPCTrainerClient(server.target)
            t0 = time.perf_counter()
            client.train(
                ip=ip, hostname=hostname, scheduler_id="bench",
                download_shards=paths,
            )
            dt = time.perf_counter() - t0
            client.close()
        finally:
            server.stop()
        return dt, None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=2.0)
    ap.add_argument("--shard-mb", type=int, default=512)
    ap.add_argument("--device", action="store_true",
                    help="also measure host->device transfer (uses the chip)")
    ap.add_argument("--work-dir", default=None,
                    help="where shards + staging live (default: system tmp; "
                    "pass /dev/shm to isolate the software path from the "
                    "sandbox's ~170 MB/s virtual disk)")
    args = ap.parse_args()

    from dragonfly2_tpu.records.columnar import concat_readers
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
    from dragonfly2_tpu.trainer.service import TrainerService

    width = len(DOWNLOAD_COLUMNS)
    total = int(args.gb * (1 << 30))
    src_dir = tempfile.mkdtemp(prefix="wire-src-", dir=args.work_dir)
    results = {}
    try:
        t0 = time.perf_counter()
        paths = make_shards(src_dir, total, args.shard_mb << 20)
        gen_s = time.perf_counter() - t0
        nbytes = sum(os.path.getsize(p) for p in paths)
        n_rows = nbytes // (4 * width) - len(paths)  # headers excluded approx
        print(f"wire-ingest: {len(paths)} shards, {nbytes / 1e9:.2f} GB, "
              f"~{n_rows / 1e6:.1f}M records ({gen_s:.1f}s gen)", flush=True)

        for kind in ("http", "grpc"):
            stage_dir = tempfile.mkdtemp(
                prefix=f"wire-stage-{kind}-", dir=args.work_dir
            )
            service = TrainerService(data_dir=stage_dir)
            # Ingest bench: the on-EOF training kick is out of scope.
            service._run_training = lambda run, session: run.done.set()
            hostname = f"bench-{kind}"
            dt, _ = run_transport(
                kind, service, paths, ip="10.0.0.9", hostname=hostname
            )
            # Decode the STAGED bytes exactly as _train_mlp does.
            staged = []
            for root, _, files in os.walk(stage_dir):
                staged += [os.path.join(root, f) for f in files]
            t0 = time.perf_counter()
            rows = concat_readers(sorted(staged))
            decode_s = time.perf_counter() - t0
            assert rows.shape[0] >= n_rows * 0.99, (rows.shape, n_rows)
            results[kind] = {
                "wire_s": round(dt, 2),
                "wire_MBps": round(nbytes / dt / 1e6, 1),
                "wire_records_per_s": round(rows.shape[0] / dt, 1),
                "decode_s": round(decode_s, 2),
                "decode_records_per_s": round(rows.shape[0] / decode_s, 1),
                "e2e_records_per_s": round(rows.shape[0] / (dt + decode_s), 1),
            }
            print(json.dumps({kind: results[kind]}), flush=True)
            del rows
            shutil.rmtree(stage_dir, ignore_errors=True)

        if args.device:
            import jax
            import jax.numpy as jnp

            rows = concat_readers(paths)
            batch = 131_072 * 64
            t0 = time.perf_counter()
            moved = 0
            for start in range(0, rows.shape[0], batch):
                arr = jnp.asarray(rows[start : start + batch])
                arr.block_until_ready()
                moved += arr.size * 4
            dev_s = time.perf_counter() - t0
            results["device"] = {
                "transfer_s": round(dev_s, 2),
                "transfer_MBps": round(moved / dev_s / 1e6, 1),
                "records_per_s": round(rows.shape[0] / dev_s, 1),
                "platform": jax.devices()[0].platform,
            }
            print(json.dumps({"device": results["device"]}), flush=True)

        print(json.dumps({
            "bench": "wire_ingest",
            "work_dir": args.work_dir or tempfile.gettempdir(),
            "gb": round(nbytes / 1e9, 2),
            "record_bytes": 4 * width,
            "north_star_records_per_s": 1.3e6,
            "results": results,
        }), flush=True)
    finally:
        shutil.rmtree(src_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    main()
