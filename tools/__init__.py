"""Measurement harnesses (scripts) and the dflint static analyzer
(``python -m tools.dflint``).  The scripts stay directly runnable; this
package marker exists so dflint is importable as ``tools.dflint``."""
