"""Mesh-mode online graph trainer at config[5] GRAPH scale (VERDICT r4 #1).

The r4 record topped out at 100k nodes × K=16 (~1.6M table edges); the
"1B edges fits a v5e-16" claim was extrapolated.  This bench drives the
REAL pipeline — wire-fed topology rows → WireIngestAdapter (native
engine) → bounded window → ``build_neighbor_table`` →
``build_halo_plan`` → node-sharded ``precompute_hop_features_sharded``
→ mesh-mode training dispatches — at ≥2^20 nodes × K=32 (≥33.5M table
edges, ~20× the prior record) on an n-device virtual mesh, and measures
the numbers the extrapolation needs:

- per-device XLA memory (args + temps) of the sharded precompute AND the
  train dispatch, vs the replicated program;
- halo size H at each locality (the deployment shape is rack-biased
  probes, SURVEY §5.7; locality 0 is the adversarial bound);
- wall time for the full snapshot refresh (table + plan + precompute);
- sustained training rec/s (CPU-mesh wall times are single-core
  time-multiplexed — the SHAPE of the scaling is the datum, per-chip
  rates come from the TPU benches).

The max-graph-per-chip model (validated against the measured points, see
BENCHMARKS.md): per-chip node-table bytes ≈
    (S + n·H) · (F + D) · 4   [hop feats + features through the halo]
  +  S · K · 12               [table rows: idx4 + mask4 + edge feat4]
  +  S · E · 12               [embedding + 2 Adam moments]
with S = N/n.  Solving 1B edges (N=2^25, K=32) for per-chip HBM gives
the v5e-16 claim as a curve instead of a hope.

Usage (single config):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/bench_graph_scale.py --nodes 1048576 --k 32 \
      --model-axis 8 --locality 0.9
Sweep (spawns one subprocess per config):
  python tools/bench_graph_scale.py --sweep
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def local_graph(n, shard, rng, locality, k_edges):
    """Edges where ~locality of each node's probers live on its shard."""
    dst = rng.integers(0, n, k_edges)
    local = rng.random(k_edges) < locality
    shard_of = dst // shard
    src_local = shard_of * shard + rng.integers(0, shard, k_edges)
    src_any = rng.integers(0, n, k_edges)
    src = np.where(local, src_local, src_any)
    keep = src != dst
    return src[keep].astype(np.int64), dst[keep].astype(np.int64)


def run_config(args) -> dict:
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.models.hop import HopConfig, precompute_hop_features
    from dragonfly2_tpu.parallel.graph_sharding import (
        build_halo_plan,
        precompute_hop_features_sharded,
    )
    from dragonfly2_tpu.parallel.mesh import MODEL_AXIS, MeshSpec, create_mesh
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
    from dragonfly2_tpu.trainer.online_graph import (
        OnlineGraphConfig,
        OnlineGraphTrainer,
    )
    from dragonfly2_tpu.trainer.train import TrainConfig

    from dragonfly2_tpu.records.features import NUM_HASH_BUCKETS

    n_dev = len(jax.devices())
    data_axis = max(n_dev // args.model_axis, 1)
    mesh = create_mesh(MeshSpec(data=data_axis, model=args.model_axis))
    N, K = args.nodes, args.k
    # The wire keys hosts by hash bucket in float32 rows: the bucket
    # space is 2^20 (exact in float32).  Beyond it, ids would silently
    # round (2^24+) or alias — the >2^20 extrapolation in BENCHMARKS.md
    # is the measured MEMORY model, not a wire-format claim.
    if N > NUM_HASH_BUCKETS:
        raise SystemExit(
            f"--nodes {N} exceeds the wire bucket space "
            f"({NUM_HASH_BUCKETS}); the composed wire path cannot key "
            f"that many distinct hosts per trainer"
        )
    S = N // args.model_axis
    rng = np.random.default_rng(0)

    cfg = OnlineGraphConfig(
        num_nodes=N,
        max_neighbors=K,
        batch_size=args.batch,
        super_steps=args.super_steps,
        topo_window=N * K + N,  # full edge stream + the registration ring
        queue_capacity=2,
        mesh=mesh,
        node_sharding="model",
        model=HopConfig(hidden=args.hidden, node_embed_dim=32),
        train=TrainConfig(warmup_steps=10),
        total_steps_hint=10_000,
    )
    trainer = OnlineGraphTrainer(
        cfg,
        node_feats=np.zeros((N, 12), np.float32),
        topo_src=np.zeros(0, np.int32),
        topo_dst=np.zeros(0, np.int32),
        topo_rtt=np.zeros(0, np.float32),
    )
    adapter = trainer.make_wire_adapter()
    native = adapter._native is not None

    # Register buckets in ascending order so bucket→dense-id is identity
    # and the locality structure survives the wire mapping.  The N ring
    # edges are noise amid N·K real ones (and counted in the window).
    ring = np.zeros((N, 3), np.float32)
    ring[:, 0] = np.arange(N)
    ring[:, 1] = np.roll(np.arange(N), 1)
    ring[:, 2] = 0.01
    t0 = time.perf_counter()
    for i in range(0, N, 4_000_000):
        adapter.feed_topology_rows(ring[i : i + 4_000_000])
    # The real probe stream, wire-shaped chunks.
    src, dst = local_graph(N, S, rng, args.locality, N * K)
    edges = len(src)
    chunk = 4_000_000
    for i in range(0, edges, chunk):
        rows = np.zeros((min(chunk, edges - i), 3), np.float32)
        rows[:, 0] = src[i : i + chunk]
        rows[:, 1] = dst[i : i + chunk]
        rows[:, 2] = rng.random(len(rows)).astype(np.float32) * 0.05
        adapter.feed_topology_rows(rows)
    t_feed = time.perf_counter() - t0
    assert adapter.overflow_edges == 0, adapter.overflow_edges

    # Snapshot refresh — the full wire-fed pipeline, timed end to end.
    t0 = time.perf_counter()
    assert trainer.refresh_snapshot() is not None
    t_refresh = time.perf_counter() - t0

    # Memory analysis of the real programs at this shape.
    def mem(jitted, *a):
        try:
            m = jitted.lower(*a).compile().memory_analysis()
            return int(m.argument_size_in_bytes + m.temp_size_in_bytes)
        except Exception:  # noqa: BLE001
            return -1

    table, nf = trainer.table, jnp.asarray(trainer.node_feats)
    t0 = time.perf_counter()
    plan = build_halo_plan(table, mesh, axis=MODEL_AXIS)
    t_plan = time.perf_counter() - t0
    sh_fn = jax.jit(
        lambda x, t: precompute_hop_features_sharded(
            mesh, x, t, plan, hops=cfg.model.hops, axis=MODEL_AXIS
        )
    )
    mem_sh = mem(sh_fn, nf, table)
    mem_rep = -1
    if args.replicated_baseline:
        rep_fn = jax.jit(
            lambda x, t: precompute_hop_features(x, t, hops=cfg.model.hops)
        )
        mem_rep = mem(rep_fn, nf, table)
    # Train-dispatch program (state + hop tables + block).
    blk = (args.super_steps, args.batch)
    mem_dispatch = mem(
        trainer._dispatch_fn, trainer.state, trainer.hop_feats, trainer.table,
        jnp.zeros(blk, jnp.int32), jnp.zeros(blk, jnp.int32),
        jnp.zeros(blk, jnp.float32),
    )

    # A few training dispatches through the wire adapter (download rows);
    # the feeder runs concurrently — the edge ring applies backpressure.
    import threading

    need = args.dispatches * args.super_steps * args.batch
    w = len(DOWNLOAD_COLUMNS)

    def feeder():
        frng = np.random.default_rng(1)
        fed = 0
        while fed < need:
            m = min(1_000_000, need - fed)
            rows = frng.random((m, w)).astype(np.float32)
            rows[:, 0] = frng.integers(0, N, m)
            rows[:, 1] = (rows[:, 0] + 1 + frng.integers(0, N - 1, m)) % N
            adapter.feed_download_rows(rows)
            fed += m
        trainer.end_of_stream()

    t0 = time.perf_counter()
    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    ran = trainer.run(max_dispatches=args.dispatches, idle_timeout=60.0)
    t_train = time.perf_counter() - t0
    th.join(timeout=60)
    trainer.close()

    return {
        "nodes": N,
        "k": K,
        "table_edges": int(np.asarray(table.mask).sum()),
        "devices": n_dev,
        "mesh": {"data": data_axis, "model": args.model_axis},
        "locality": args.locality,
        "native_ingest": native,
        "halo": int(plan.halo),
        "shard_rows": S,
        "rows_per_dev_sharded": int(S + args.model_axis * plan.halo),
        "t_wire_feed_s": round(t_feed, 1),
        "t_refresh_total_s": round(t_refresh, 1),
        "t_plan_s": round(t_plan, 1),
        "mem_sharded_per_dev_bytes": mem_sh,
        "mem_replicated_per_dev_bytes": mem_rep,
        "mem_dispatch_per_dev_bytes": mem_dispatch,
        "dispatches": ran,
        "records_trained": trainer.records_seen,
        "rec_per_s_cpu_mesh": round(trainer.records_seen / max(t_train, 1e-9), 1),
    }


SWEEP = [
    # (devices, model_axis, locality, nodes, k, replicated_baseline)
    (8, 8, 0.9, 1 << 20, 32, True),   # headline: 20x the r4 graph record
    (8, 8, 0.0, 1 << 20, 32, False),  # adversarial locality bound
    (4, 4, 0.9, 1 << 20, 32, False),  # device-count scaling...
    (16, 16, 0.9, 1 << 20, 32, False),
    (8, 8, 0.9, 1 << 17, 32, True),   # continuity point near the r4 shape
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1 << 20)
    ap.add_argument("--k", type=int, default=32)
    ap.add_argument("--model-axis", type=int, default=8)
    ap.add_argument("--locality", type=float, default=0.9)
    ap.add_argument("--batch", type=int, default=65_536)
    ap.add_argument("--super", dest="super_steps", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--dispatches", type=int, default=2)
    ap.add_argument("--replicated-baseline", action="store_true")
    ap.add_argument("--sweep", action="store_true")
    args = ap.parse_args()

    if not args.sweep:
        out = run_config(args)
        print(json.dumps(out), flush=True)
        return 0

    results = []
    for devs, ma, loc, nodes, k, rep in SWEEP:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--nodes", str(nodes), "--k", str(k),
            "--model-axis", str(ma), "--locality", str(loc),
        ] + (["--replicated-baseline"] if rep else [])
        print(f"# sweep: devices={devs} model={ma} locality={loc} "
              f"nodes={nodes}", flush=True)
        t0 = time.time()
        try:
            proc = subprocess.run(
                cmd, env=env, capture_output=True, text=True, timeout=3600
            )
        except subprocess.TimeoutExpired:
            print(f"# TIMEOUT after 3600s: devices={devs} model={ma}",
                  flush=True)
            continue
        line = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        if proc.returncode != 0 or not line:
            print(f"# FAILED rc={proc.returncode}: {proc.stderr[-800:]}",
                  flush=True)
            continue
        r = json.loads(line[-1])
        r["wall_s"] = round(time.time() - t0, 1)
        results.append(r)
        print(json.dumps(r), flush=True)
    print(json.dumps({"bench": "graph_scale_sweep", "results": results}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
