"""Sharded vs replicated hop-feature precompute (VERDICT r3 weak-#4 / next-#3).

Measures, on an n-device mesh (CPU virtual mesh in the sandbox; the same
program runs on a real TPU slice):

- wall time: jit(precompute_hop_features) with the FULL table on every
  device vs jit(precompute_hop_features_sharded) (node-sharded, halo
  all-to-all per hop);
- per-device working set: XLA memory_analysis (args + temps) for both
  programs, plus the analytic table bytes (N rows replicated vs
  S + n_shards*halo rows per shard);
- the halo itself (H vs S) at each graph locality — the win is
  locality-dependent, so both the locality-partitioned case (deployment
  assumption: probes are rack/cluster-biased, SURVEY §5.7) and the
  random worst case are reported.

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/bench_sharded_precompute.py [--nodes 131072]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def local_graph(n, shard, rng, locality, k_edges):
    """Graph where ~locality of edges stay within a node's shard."""
    dst = rng.integers(0, n, k_edges)
    local = rng.random(k_edges) < locality
    shard_of = dst // shard
    src_local = shard_of * shard + rng.integers(0, shard, k_edges)
    src_any = rng.integers(0, n, k_edges)
    src = np.where(local, src_local, src_any)
    return src.astype(np.int64), dst.astype(np.int64)


def timed(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps, out


def mem_analysis(jitted, *args):
    try:
        m = jitted.lower(*args).compile().memory_analysis()
        return int(m.argument_size_in_bytes + m.temp_size_in_bytes)
    except Exception:  # noqa: BLE001 — backend without the analysis
        return -1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=131_072)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--hops", type=int, default=2)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    from dragonfly2_tpu.models import build_neighbor_table
    from dragonfly2_tpu.models.hop import precompute_hop_features
    from dragonfly2_tpu.parallel.graph_sharding import (
        build_halo_plan,
        precompute_hop_features_sharded,
    )
    from dragonfly2_tpu.parallel.mesh import MeshSpec, create_mesh

    n_dev = len(jax.devices())
    mesh = create_mesh(MeshSpec(data=n_dev, model=1))
    n, d, k = args.nodes, args.dim, args.k
    shard = n // n_dev
    rng = np.random.default_rng(0)
    nf = rng.normal(size=(n, d)).astype(np.float32)

    rep_fn = jax.jit(lambda x, t: precompute_hop_features(x, t, hops=args.hops))
    rows = []
    for locality in (0.9, 0.0):
        src, dst = local_graph(n, shard, rng, locality, n * 8)
        feats = rng.random(len(src)).astype(np.float32)
        table = build_neighbor_table(n, src, dst, feats, max_neighbors=k)

        t_rep, want = timed(rep_fn, jnp.asarray(nf), table, reps=args.reps)
        mem_rep = mem_analysis(rep_fn, jnp.asarray(nf), table)

        t0 = time.perf_counter()
        plan = build_halo_plan(table, mesh)
        t_plan = time.perf_counter() - t0
        sh_fn = jax.jit(
            lambda x, t, p=plan: precompute_hop_features_sharded(
                mesh, x, t, p, hops=args.hops
            )
        )
        t_sh, got = timed(sh_fn, jnp.asarray(nf), table, reps=args.reps)
        mem_sh = mem_analysis(sh_fn, jnp.asarray(nf), table)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

        # Analytic per-device node-table rows (the memory-wall term).
        rows_rep = n
        rows_sh = shard + n_dev * plan.halo
        rows.append(
            {
                "locality": locality,
                "halo": int(plan.halo),
                "shard": int(shard),
                "t_replicated_s": round(t_rep, 4),
                "t_sharded_s": round(t_sh, 4),
                "t_plan_build_s": round(t_plan, 4),
                "mem_replicated_bytes": mem_rep,
                "mem_sharded_bytes": mem_sh,
                "table_rows_per_dev_replicated": rows_rep,
                "table_rows_per_dev_sharded": rows_sh,
                "table_rows_ratio": round(rows_sh / rows_rep, 4),
            }
        )
        print(json.dumps(rows[-1]), flush=True)

    print(
        json.dumps(
            {
                "bench": "sharded_precompute",
                "devices": n_dev,
                "nodes": n,
                "dim": d,
                "k": k,
                "hops": args.hops,
                "results": rows,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
