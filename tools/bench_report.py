"""Aggregate ``BENCH_r*.json`` into the committed perf-trajectory table.

Every bench round the driver runs leaves one ``BENCH_rNN.json`` at the
repo root (``bench.py``'s structured envelope: ``rc``, ``parsed``
payload, optional ``note``).  Until this tool, reading the trajectory —
which rounds held the headline, which died, which were structured skips
or CPU-fallback smoke rounds — meant re-parsing raw JSON by hand every
PR.  This renders the whole history as one markdown table between
markers in ``BENCHMARKS.md``:

    <!-- bench:trajectory:begin --> ... <!-- bench:trajectory:end -->

and is staleness-checked in tier-1 (``tests/test_bench_report.py``)
exactly like the §16 lock graph and the compile budget: a new round (or
an edited old one) fails the suite until the committed table is
regenerated with::

    python -m tools.bench_report --update

Row semantics (one per round, in round order):

- ``ok``       — the payload parsed and ``rc == 0``; headline value,
                 backend (``tpu`` unless the payload says otherwise),
                 step time and MFU when present;
- ``skipped``  — a structured skip (``parsed.skipped``, e.g.
                 ``backend_unavailable``): the round is accounted, not
                 lost;
- ``guarded``  — ok, but the regression guard fired
                 (``parsed.regression_warning``): the value is real but
                 flagged against the last good round;
- ``error``    — ``rc != 0`` / no parsed payload (the BENCH_r05-class
                 lost round this table exists to make visible).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional

TRAJECTORY_BEGIN = "<!-- bench:trajectory:begin -->"
TRAJECTORY_END = "<!-- bench:trajectory:end -->"
DOWNLOAD_BEGIN = "<!-- bench:download:begin -->"
DOWNLOAD_END = "<!-- bench:download:end -->"
TELEMETRY_BEGIN = "<!-- bench:telemetry:begin -->"
TELEMETRY_END = "<!-- bench:telemetry:end -->"
SWARM_BEGIN = "<!-- bench:swarm:begin -->"
SWARM_END = "<!-- bench:swarm:end -->"
QOS_BEGIN = "<!-- bench:qos:begin -->"
QOS_END = "<!-- bench:qos:end -->"
LIFECYCLE_BEGIN = "<!-- bench:lifecycle:begin -->"
LIFECYCLE_END = "<!-- bench:lifecycle:end -->"

_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")
_DL_ROUND_RE = re.compile(r"^BENCH_DL_r(\d+)\.json$")
_TEL_ROUND_RE = re.compile(r"^TELEMETRY_r(\d+)\.json$")
_SW_ROUND_RE = re.compile(r"^BENCH_SW_r(\d+)\.json$")
_QOS_ROUND_RE = re.compile(r"^BENCH_QOS_r(\d+)\.json$")
_LC_ROUND_RE = re.compile(r"^BENCH_LC_r(\d+)\.json$")


def collect_rounds(root: Path) -> List[dict]:
    """All bench rounds at ``root``, sorted by round number.  Each dict
    gains ``round`` (int) and ``file`` (name) keys."""
    out: List[dict] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"rc": -1, "parsed": None}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def collect_download_rounds(root: Path) -> List[dict]:
    """All download-plane rounds (``tools/bench_download.py`` →
    ``BENCH_DL_r*.json``), sorted by round number."""
    out: List[dict] = []
    for path in sorted(root.glob("BENCH_DL_r*.json")):
        m = _DL_ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"ok": False, "error": "unparseable"}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def collect_telemetry_rounds(root: Path) -> List[dict]:
    """All fleet-telemetry drill rounds (``python -m
    dragonfly2_tpu.sim.telemetry --out TELEMETRY_r*.json``), sorted by
    round number."""
    out: List[dict] = []
    for path in sorted(root.glob("TELEMETRY_r*.json")):
        m = _TEL_ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"ok": False, "error": "unparseable"}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def collect_swarm_rounds(root: Path) -> List[dict]:
    """All fleet-swarm rounds (``tools/bench_swarm.py`` →
    ``BENCH_SW_r*.json``), sorted by round number."""
    out: List[dict] = []
    for path in sorted(root.glob("BENCH_SW_r*.json")):
        m = _SW_ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"ok": False, "error": "unparseable"}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def collect_qos_rounds(root: Path) -> List[dict]:
    """All multi-tenant QoS isolation rounds (``tools/bench_qos.py`` →
    ``BENCH_QOS_r*.json``), sorted by round number."""
    out: List[dict] = []
    for path in sorted(root.glob("BENCH_QOS_r*.json")):
        m = _QOS_ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"ok": False, "error": "unparseable"}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def render_qos(rounds: List[dict]) -> str:
    """The generated QoS-isolation block, markers included (one row per
    BENCH_QOS round: the isolation score, tenant A's p99/TTLB movement
    under the shaped burst vs the unshaped interference baseline, and
    the shaped arm's shed/cap evidence)."""
    lines = [
        QOS_BEGIN,
        "Generated by `python -m tools.bench_report --update` from the",
        "`BENCH_QOS_r*.json` rounds (tools/bench_qos.py) — do not edit",
        "by hand; tier-1 (`tests/test_bench_report.py`) fails if stale.",
        "",
        "| round | status | isolation score | shaped Δp99 / ΔTTLB | "
        "unshaped Δp99 / ΔTTLB | flood shed/capped | note |",
        "| --- | --- | --- | --- | --- | --- | --- |",
    ]
    for data in rounds:
        move = data.get("movement") or {}
        shaped = (data.get("arms") or {}).get("shaped") or {}
        if not data.get("ok") or not move:
            lines.append(
                f"| r{data['round']:02d} | error | — | — | — | — | "
                f"{str(data.get('error', ''))[:80]} |"
            )
            continue
        status = "guarded" if data.get("regression_warning") else "ok"
        note = str(data.get("note", "") or "").replace("|", "\\|")
        lines.append(
            f"| r{data['round']:02d} | {status} "
            f"| {data.get('value', 0):.1f} "
            f"| {move.get('shaped_announce_p99_pct', 0):+.1f}% / "
            f"{move.get('shaped_ttlb_pct', 0):+.1f}% "
            f"| {move.get('unshaped_announce_p99_pct', 0):+.1f}% / "
            f"{move.get('unshaped_ttlb_pct', 0):+.1f}% "
            f"| {shaped.get('b_sheds', 0)}/{shaped.get('b_throttled', 0)} "
            f"| {note} |"
        )
    lines.append(QOS_END)
    return "\n".join(lines)


def collect_lifecycle_rounds(root: Path) -> List[dict]:
    """All self-driving-lifecycle rounds (``tools/bench_lifecycle.py`` →
    ``BENCH_LC_r*.json``), sorted by round number."""
    out: List[dict] = []
    for path in sorted(root.glob("BENCH_LC_r*.json")):
        m = _LC_ROUND_RE.match(path.name)
        if m is None:
            continue
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, json.JSONDecodeError):
            data = {"ok": False, "error": "unparseable"}
        data["round"] = int(m.group(1))
        data["file"] = path.name
        out.append(data)
    out.sort(key=lambda d: d["round"])
    return out


def render_lifecycle(rounds: List[dict]) -> str:
    """The generated lifecycle block, markers included (one row per
    BENCH_LC round: the records-in → ACTIVE-out loop latency, the
    regression-to-rollback and bounce-resume walls, and the feed-side
    records/sec)."""
    lines = [
        LIFECYCLE_BEGIN,
        "Generated by `python -m tools.bench_report --update` from the",
        "`BENCH_LC_r*.json` rounds (tools/bench_lifecycle.py) — do not",
        "edit by hand; tier-1 (`tests/test_bench_report.py`) fails if stale.",
        "",
        "| round | status | records→ACTIVE | regression→rollback | "
        "bounce resume | records/s | drill | note |",
        "| --- | --- | --- | --- | --- | --- | --- | --- |",
    ]
    for data in rounds:
        if not data.get("ok"):
            lines.append(
                f"| r{data['round']:02d} | error | — | — | — | — | — | "
                f"{str(data.get('error', ''))[:80]} |"
            )
            continue
        note = str(data.get("note", "") or "").replace("|", "\\|")
        lines.append(
            f"| r{data['round']:02d} | ok "
            f"| {data.get('records_to_active_s', 0):.2f} s "
            f"| {data.get('regression_to_rollback_s', 0):.2f} s "
            f"| {data.get('bounce_resume_s', 0):.2f} s "
            f"| {data.get('records_per_sec', 0):.0f} "
            f"| {'pass' if data.get('drill_ok') else 'FAIL'} "
            f"| {note} |"
        )
    lines.append(LIFECYCLE_END)
    return "\n".join(lines)


def render_swarm(rounds: List[dict]) -> str:
    """The generated fleet-swarm block, markers included (one row per
    BENCH_SW round: aggregate announces/sec across shards, the honest
    N-vs-1 ratio, peers driven, and the membership-drill outcome)."""
    lines = [
        SWARM_BEGIN,
        "Generated by `python -m tools.bench_report --update` from the",
        "`BENCH_SW_r*.json` rounds (tools/bench_swarm.py) — do not edit",
        "by hand; tier-1 (`tests/test_bench_report.py`) fails if stale.",
        "",
        "| round | status | aggregate ann/s (1 shard → N) | N÷1 | "
        "peers driven | max hosts/shard | drill (handoffs/redirects/"
        "dl-fail) | note |",
        "| --- | --- | --- | --- | --- | --- | --- | --- |",
    ]
    for data in rounds:
        arms = data.get("arms") or {}
        drill = data.get("membership_drill") or {}
        if not data.get("ok") or not arms:
            lines.append(
                f"| r{data['round']:02d} | error | — | — | — | — | — | "
                f"{str(data.get('error', ''))[:80]} |"
            )
            continue
        status = "guarded" if data.get("regression_warning") else "ok"
        single = arms.get("single", {})
        sharded = arms.get("sharded", {})
        note = str(data.get("note", "") or "").replace("|", "\\|")
        drill_cell = (
            f"{drill.get('handed_off_tasks', 0)}/"
            f"{drill.get('redirects_followed', 0)}/"
            f"{sharded.get('downloads_failed', 0)}"
            if drill.get("ran") else "—"
        )
        lines.append(
            f"| r{data['round']:02d} | {status} "
            f"| {single.get('announces_per_sec', 0):,.0f} → "
            f"{sharded.get('announces_per_sec', 0):,.0f} "
            f"| {data.get('speedup_shards', 0):.2f}× "
            f"| {data.get('unique_hosts', 0):,} "
            f"| {sharded.get('hosts_per_shard_max', 0):,} "
            f"| {drill_cell} "
            f"| {note} |"
        )
    lines.append(SWARM_END)
    return "\n".join(lines)


def render_telemetry(rounds: List[dict]) -> str:
    """The generated fleet-telemetry block, markers included (one row
    per TELEMETRY round: journal frames admitted/rejected, the sketch
    error bound and the measured p99 error vs the exact oracle, and the
    SLO burn-rate drill outcome)."""
    lines = [
        TELEMETRY_BEGIN,
        "Generated by `python -m tools.bench_report --update` from the",
        "`TELEMETRY_r*.json` drill rounds (python -m",
        "dragonfly2_tpu.sim.telemetry) — do not edit by hand; tier-1",
        "(`tests/test_bench_report.py`) fails if stale.",
        "",
        "| round | status | journals | frames (rejected) | sketch α | "
        "p99 rel-err | burn alert | replay parity | note |",
        "| --- | --- | --- | --- | --- | --- | --- | --- | --- |",
    ]
    for data in rounds:
        kill = data.get("kill_drill") or {}
        burn = data.get("burnrate_drill") or {}
        if not data.get("ok") or not kill or not burn:
            lines.append(
                f"| r{data['round']:02d} | error | — | — | — | — | — | — | "
                f"{str(data.get('error', ''))[:80]} |"
            )
            continue
        p99 = (kill.get("quantile_checks") or {}).get("p99") or {}
        fired = burn.get("fired_after_s")
        cleared = burn.get("cleared_after_s")
        note = (
            f"victim SIGKILLed mid-storm; torn tail tolerated"
        )
        lines.append(
            f"| r{data['round']:02d} | ok "
            f"| {kill.get('children', 0)} (1 killed) "
            f"| {kill.get('frames_admitted', 0)} "
            f"({kill.get('corrupt_rejected', 0)} rejected) "
            f"| {data.get('sketch_alpha', 0):g} "
            f"| {p99.get('rel_error', 0):.4f} "
            f"| fired {fired:.2f}s / cleared {cleared:.2f}s "
            f"| drift {burn.get('replay_burn_drift', 0):.3f} "
            f"| {note} |"
        )
    lines.append(TELEMETRY_END)
    return "\n".join(lines)


def render_download(rounds: List[dict]) -> str:
    """The generated download-plane block, markers included (one row per
    BENCH_DL round: engine, single/swarm MB/s, speedups, the ISSUE-14
    pass-through stream arms with their zero-disk-read evidence, and
    p50/p99 piece latency).  Pre-stream rounds (r01) render ``—`` in the
    stream cells; pre-§28 rounds render ``—`` in the per-core/native
    cells (``MB/s/core`` = the guarded per-core headline; ``native×`` =
    the in-engine client arm's single-peer per-core ratio)."""
    lines = [
        DOWNLOAD_BEGIN,
        "Generated by `python -m tools.bench_report --update` from the",
        "`BENCH_DL_r*.json` rounds (tools/bench_download.py) — do not edit",
        "by hand; tier-1 (`tests/test_bench_report.py`) fails if stale.",
        "",
        "| round | status | engine | single MB/s (legacy → pipelined) | "
        "speedup | MB/s/core | native× | swarm MB/s | speedup | "
        "stream MB/s (disk → tee) | "
        "stream× | tee disk reads | piece p50/p99 ms | note |",
        "| --- | --- | --- | --- | --- | --- | --- | --- | --- | --- | "
        "--- | --- | --- | --- |",
    ]
    for data in rounds:
        arms = data.get("arms") or {}
        if not data.get("ok") or not arms:
            lines.append(
                f"| r{data['round']:02d} | error | — | — | — | — | — | — | "
                f"— | — | — | — | — | {str(data.get('error', ''))[:80]} |"
            )
            continue
        status = (
            "guarded" if data.get("regression_warning") else "ok"
        )
        single = arms.get("pipelined_single", {})
        legacy = arms.get("legacy_single", {})
        swarm = arms.get("pipelined_swarm", {})
        legacy_swarm = arms.get("legacy_swarm", {})
        note = str(data.get("note", "") or "").replace("|", "\\|")
        engine = str((data.get("config") or {}).get("engine", "py"))
        s_disk = arms.get("stream_disk")
        s_tee = arms.get("stream_tee")
        if s_disk and s_tee:
            stream_cell = (
                f"{s_disk.get('MBps', 0):.0f} → {s_tee.get('MBps', 0):.0f}"
            )
            stream_x = f"{data.get('speedup_stream', 0):.2f}×"
            st = data.get("stream") or {}
            reads_cell = (
                f"{st.get('disk_reads_tee', 0)} vs "
                f"{st.get('disk_reads_disk', 0)}"
            )
        else:
            stream_cell = stream_x = reads_cell = "—"
        per_core = single.get("MBps_per_core")
        per_core_cell = "—" if per_core is None else f"{per_core:.0f}"
        native_x = (data.get("native") or {}).get("speedup_native_single")
        native_cell = "—" if native_x is None else f"{native_x:.2f}×"
        lines.append(
            f"| r{data['round']:02d} | {status} "
            f"| {engine} "
            f"| {legacy.get('MBps', 0):.0f} → {single.get('MBps', 0):.0f} "
            f"| {data.get('speedup_single', 0):.2f}× "
            f"| {per_core_cell} | {native_cell} "
            f"| {legacy_swarm.get('MBps', 0):.0f} → {swarm.get('MBps', 0):.0f} "
            f"| {data.get('speedup_swarm', 0):.2f}× "
            f"| {stream_cell} | {stream_x} | {reads_cell} "
            f"| {single.get('p50_ms', 0):.1f} / {single.get('p99_ms', 0):.1f} "
            f"| {note} |"
        )
    lines.append(DOWNLOAD_END)
    return "\n".join(lines)


def _fmt_value(value: Optional[float], unit: str) -> str:
    if value is None:
        return "—"
    v = float(value)
    if v >= 1e6:
        text = f"{v / 1e6:.2f}M"
    elif v >= 1e3:
        text = f"{v / 1e3:.1f}k"
    else:
        text = f"{v:.1f}"
    return f"{text} {unit}".strip()


def _row_of(data: dict) -> Dict[str, str]:
    parsed = data.get("parsed") or {}
    rc = data.get("rc", -1)
    note = str(data.get("note", "") or "")
    # A round that ran against a standby/promoted manager (HA drills)
    # is accounted as such — its control-plane latencies aren't
    # comparable to leader-served rounds.
    if parsed.get("standby") or data.get("standby"):
        note = f"{note}; ran against standby manager" if note else (
            "ran against standby manager"
        )
    if not parsed or rc != 0:
        status = f"error (rc={rc})"
        backend = "—"
        value = "—"
        step = mfu = "—"
    else:
        backend = str(parsed.get("backend") or "tpu")
        value = _fmt_value(parsed.get("value"), str(parsed.get("unit", "")))
        step = (
            f"{parsed['step_ms']:.1f} ms" if parsed.get("step_ms") is not None
            else "—"
        )
        mfu = (
            f"{parsed['mfu'] * 100:.1f}%" if parsed.get("mfu") is not None
            else "—"
        )
        if parsed.get("skipped"):
            status = f"skipped ({parsed['skipped']})"
        elif parsed.get("regression_warning"):
            warn = parsed["regression_warning"]
            status = (
                f"guarded (×{warn.get('dropped_to', '?')} of "
                f"r{warn.get('vs_round', '?')})"
            )
        else:
            status = "ok"
    return {
        "round": f"r{data['round']:02d}",
        "status": status,
        "value": value,
        "backend": backend,
        "step": step,
        "mfu": mfu,
        "note": note.replace("|", "\\|"),
    }


def render_trajectory(rounds: List[dict]) -> str:
    """The generated BENCHMARKS.md block, markers included."""
    lines = [
        TRAJECTORY_BEGIN,
        "Generated by `python -m tools.bench_report --update` — do not edit",
        "by hand; tier-1 (`tests/test_bench_report.py`) fails if this table",
        "drifts from the `BENCH_r*.json` rounds on disk.",
        "",
        "| round | status | headline | backend | step | MFU | note |",
        "| --- | --- | --- | --- | --- | --- | --- |",
    ]
    for data in rounds:
        r = _row_of(data)
        lines.append(
            f"| {r['round']} | {r['status']} | {r['value']} | "
            f"{r['backend']} | {r['step']} | {r['mfu']} | {r['note']} |"
        )
    lines.append(TRAJECTORY_END)
    return "\n".join(lines)


def _replace_block(text: str, begin_marker: str, end_marker: str,
                   fresh: str, *, required: bool = True) -> str:
    begin = text.find(begin_marker)
    end = text.find(end_marker)
    if begin < 0 or end < 0:
        if required:
            raise SystemExit(
                f"markers not found ({begin_marker} ... {end_marker})"
            )
        return text
    return text[:begin] + fresh + text[end + len(end_marker):]


def update_file(
    path: Path,
    rounds: List[dict],
    dl_rounds: Optional[List[dict]] = None,
    tel_rounds: Optional[List[dict]] = None,
    sw_rounds: Optional[List[dict]] = None,
    qos_rounds: Optional[List[dict]] = None,
    lc_rounds: Optional[List[dict]] = None,
) -> bool:
    """Replace the marker-delimited block(s); True when the file changed.
    The download/telemetry/swarm/qos blocks are optional (docs without
    their markers are left untouched)."""
    text = path.read_text(encoding="utf-8")
    new = _replace_block(
        text, TRAJECTORY_BEGIN, TRAJECTORY_END, render_trajectory(rounds)
    )
    if dl_rounds is not None:
        new = _replace_block(
            new, DOWNLOAD_BEGIN, DOWNLOAD_END, render_download(dl_rounds),
            required=False,
        )
    if tel_rounds is not None:
        new = _replace_block(
            new, TELEMETRY_BEGIN, TELEMETRY_END, render_telemetry(tel_rounds),
            required=False,
        )
    if sw_rounds is not None:
        new = _replace_block(
            new, SWARM_BEGIN, SWARM_END, render_swarm(sw_rounds),
            required=False,
        )
    if qos_rounds is not None:
        new = _replace_block(
            new, QOS_BEGIN, QOS_END, render_qos(qos_rounds),
            required=False,
        )
    if lc_rounds is not None:
        new = _replace_block(
            new, LIFECYCLE_BEGIN, LIFECYCLE_END, render_lifecycle(lc_rounds),
            required=False,
        )
    if new != text:
        path.write_text(new, encoding="utf-8")
        return True
    return False


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.bench_report",
        description="render the BENCH_r*.json perf trajectory",
    )
    parser.add_argument("--root", default=".",
                        help="directory holding BENCH_r*.json (default: .)")
    parser.add_argument("--file", default="BENCHMARKS.md",
                        help="markdown file carrying the marked block")
    parser.add_argument("--update", action="store_true",
                        help="rewrite the marked block in place")
    parser.add_argument("--check", action="store_true",
                        help="exit 1 when the committed block is stale")
    args = parser.parse_args(argv)

    root = Path(args.root)
    rounds = collect_rounds(root)
    dl_rounds = collect_download_rounds(root)
    tel_rounds = collect_telemetry_rounds(root)
    sw_rounds = collect_swarm_rounds(root)
    qos_rounds = collect_qos_rounds(root)
    lc_rounds = collect_lifecycle_rounds(root)
    fresh = render_trajectory(rounds)
    fresh_dl = render_download(dl_rounds)
    fresh_tel = render_telemetry(tel_rounds)
    fresh_sw = render_swarm(sw_rounds)
    fresh_qos = render_qos(qos_rounds)
    fresh_lc = render_lifecycle(lc_rounds)
    if args.update:
        changed = update_file(
            root / args.file, rounds, dl_rounds, tel_rounds, sw_rounds,
            qos_rounds, lc_rounds,
        )
        print(
            f"{args.file}: tables "
            + ("updated" if changed else "already current")
            + f" ({len(rounds)} round(s), {len(dl_rounds)} download "
            f"round(s), {len(tel_rounds)} telemetry round(s), "
            f"{len(sw_rounds)} swarm round(s), {len(qos_rounds)} qos "
            f"round(s), {len(lc_rounds)} lifecycle round(s))"
        )
        return 0
    if args.check:
        text = (root / args.file).read_text(encoding="utf-8")
        for name, begin_m, end_m, want, optional_empty in (
            ("trajectory", TRAJECTORY_BEGIN, TRAJECTORY_END, fresh, False),
            ("download", DOWNLOAD_BEGIN, DOWNLOAD_END, fresh_dl,
             not dl_rounds),
            ("telemetry", TELEMETRY_BEGIN, TELEMETRY_END, fresh_tel,
             not tel_rounds),
            ("swarm", SWARM_BEGIN, SWARM_END, fresh_sw, not sw_rounds),
            ("qos", QOS_BEGIN, QOS_END, fresh_qos, not qos_rounds),
            ("lifecycle", LIFECYCLE_BEGIN, LIFECYCLE_END, fresh_lc,
             not lc_rounds),
        ):
            begin = text.find(begin_m)
            end = text.find(end_m)
            if begin < 0 or end < 0:
                if optional_empty:
                    continue  # no rounds yet, block optional
                print(f"{args.file}: {name} markers missing", file=sys.stderr)
                return 1
            committed = text[begin : end + len(end_m)]
            if committed != want:
                print(
                    f"{args.file}: {name} table stale — run "
                    "python -m tools.bench_report --update",
                    file=sys.stderr,
                )
                return 1
        print(
            f"{args.file}: tables current ({len(rounds)} round(s), "
            f"{len(dl_rounds)} download round(s), "
            f"{len(tel_rounds)} telemetry round(s), "
            f"{len(sw_rounds)} swarm round(s), "
            f"{len(qos_rounds)} qos round(s), "
            f"{len(lc_rounds)} lifecycle round(s))"
        )
        return 0
    print(fresh)
    print()
    print(fresh_dl)
    print()
    print(fresh_tel)
    print()
    print(fresh_sw)
    print()
    print(fresh_qos)
    print()
    print(fresh_lc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
