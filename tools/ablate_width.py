"""Width-quality ablation: does a ≥30%-MFU hop-ranker width hold val MAE?

VERDICT r2 weak-#1: the mfu_wide.py sweep showed hidden 512/1024 hitting
27/53% MFU but carried **no quality numbers and ran with dropout off** —
so the ≥30%-MFU north-star bar (BASELINE.json) stayed unmet.  This tool
closes that gap: the exact config[2] ablation workload
(tools/ablate_rankers.py — 100k-node probe graph, 2M download edges,
log1p-bandwidth targets, identical split/seed) trained at each width
with the PRODUCTION dropout (HopConfig default 0.1) and the production
train loop (train_hop_ranker).

Promotion rule (VERDICT r2 next-#1 done-condition): a width whose val
log-MAE ≤ the width-128 flagship's becomes the flagship bench config.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python tools/ablate_width.py [widths...]
Prints one JSON line per width.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from dragonfly2_tpu.models import build_neighbor_table
    from dragonfly2_tpu.models.hop import HopConfig
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.train import TrainConfig, train_hop_ranker

    widths = [int(a) for a in sys.argv[1:] if a.isdigit()] or [128, 512, 1024]
    on_tpu = jax.devices()[0].platform != "cpu"
    n_nodes = 100_000 if on_tpu else 2_000
    n_edges = 2_000_000 if on_tpu else 40_000
    epochs = 60 if on_tpu else 8

    print(
        f"# workload: {n_nodes} nodes, {n_edges} edges, {epochs} epochs, "
        f"widths {widths}", file=sys.stderr, flush=True,
    )
    cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
    src, dst, rtt = cluster.probe_edges(density=16 / max(n_nodes - 1, 1), seed=0)
    table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=16)
    nf = cluster._host_feature_matrix()

    rng = np.random.default_rng(0)
    es = rng.integers(0, n_nodes, n_edges).astype(np.int32)
    ed = (es + rng.integers(1, n_nodes, n_edges).astype(np.int32)) % n_nodes
    y = np.log1p(cluster._bandwidth_vec(es, ed)).astype(np.float32)
    cfg = TrainConfig(epochs=epochs)

    for hidden in widths:
        mcfg = HopConfig(hidden=hidden)  # production dropout (0.1) stays ON
        t0 = time.time()
        _, m, hist = train_hop_ranker(
            nf, table, es, ed, y, model_config=mcfg, config=cfg,
            batch_size=131_072,
        )
        print(json.dumps({
            "model": f"hop-h{hidden}",
            "hidden": hidden,
            "dropout": mcfg.dropout,
            "val_log_mae": round(m.mae, 4),
            "f1": round(m.f1, 4),
            "wall_s": round(time.time() - t0, 1),
            "records_per_sec": round(hist[-1]["records_per_sec"], 1) if hist else None,
        }), flush=True)


if __name__ == "__main__":
    main()
