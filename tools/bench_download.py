"""End-to-end download throughput benchmark: wall-clock MB/s through the
REAL piece data plane (scheduler RPC over HTTP + piece servers on
loopback sockets), single-peer, N-peer swarm, and pass-through stream.

Two arms per scenario, measured in INTERLEAVED rounds (bench_sched.py
discipline: one unmeasured warm round, GC quiesced, walls measured in
the downloading workers):

- ``legacy``    — the pre-PR-11 path kept as the reference: one fresh
  urllib connection per piece, whole-piece buffered serve, strictly
  sequential fetch→digest→commit→report per worker;
- ``pipelined`` — the PR-11 data plane: per-parent keep-alive connection
  pool, ``os.sendfile`` zero-copy serve, commit pipeline (digest piece N
  while N+1 is on the wire) and bounded-linger batched piece reports.

The **stream** scenario (DESIGN.md §25) measures the PASS-THROUGH shape:
N HTTP clients consume one task through the dfdaemon proxy WHILE the
P2P download runs.  Its two arms differ only in the read plane:

- ``stream_disk`` — every piece a consumer serves is read back off the
  disk it was committed to (the pre-tee path, crc-verified read);
- ``stream_tee``  — consumers ride the commit tee: the committer hands
  each verified body to all N consumers in memory, zero disk reads on
  the fast path (the per-round disk-read counts are reported as
  evidence).  Time-to-last-byte at the slowest consumer is the wall.

``--engine native`` drives the pipelined/stream arms through the C++
in-engine piece server (native.cpp ps_serve — no Python on the serve
path); the legacy arm keeps the Python reference server, so the ratio
stays "new plane vs pre-PR plane".

``--engine native-both`` (DESIGN.md §28) additionally runs a third
interleaved arm, ``nativeboth``: the CLIENT inner loop moves in-engine
too (conductor native fetch window over pf_* workers — pooled
keep-alive fetch → length check → crc commit with zero Python per
piece), and a **saturate** scenario (every client pulls a DISTINCT task
concurrently — aggregate box throughput, no inter-client piece
sharing) runs on both the pipelined and nativeboth arms.  The guarded
headline for this engine is **MB/s per core** (``MBps_per_core`` =
MBps / os.cpu_count()) so the number transfers to multi-core boxes.
Every single/saturate download is crc-checked against the origin every
round, and teardown asserts ZERO leaked native servers/connections
(ps_leak_stats).

Hedging is OFF in both arms (it is a tail-latency feature; a loopback
bench would never trigger it and enabling it only on one arm would skew
the comparison).

Reports MB/s and p50/p99 per-piece fetch latency per arm, the
``speedup_single`` / ``speedup_swarm`` / ``speedup_stream`` ratios
(acceptance bars: single ≥ 2×, stream ≥ 1.5×), pool reuse stats and
server sendfile counts as evidence the fast arm really exercised the
new plane, and a regression guard over ``BENCH_DL_r*.json`` rounds at
the repo root (bench.py's ``apply_regression_guard`` applied to the
download headline).

Usage: PYTHONPATH=/root/repo python tools/bench_download.py
       [--piece-mb 4] [--pieces 16] [--rounds 3] [--swarm 3]
       [--parallelism 4] [--stream-consumers 3] [--engine py|native]
       [--seed 0]
       [--smoke]   # tiny sizes: the tier-1 JSON-schema gate
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import re
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "arms",
    "speedup_single",
    "speedup_swarm",
    "speedup_stream",
    "pool",
    "serve",
    "stream",
    "native",
)

ARM_KEYS = (
    "MBps", "MBps_per_core", "p50_ms", "p99_ms", "pieces", "bytes", "wall_s",
)


def last_good_download(repo_dir: Optional[str] = None) -> dict:
    """Most recent BENCH_DL_r*.json with a parsed single-peer headline —
    the download plane's regression bar (bench.py discipline)."""
    repo_dir = repo_dir or str(Path(__file__).resolve().parents[1])
    best: dict = {}
    for path in glob.glob(os.path.join(repo_dir, "BENCH_DL_r*.json")):
        m = re.search(r"BENCH_DL_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        arm = data.get("arms", {}).get("pipelined_single") or {}
        # Per-core headline (§28): older rounds recorded only MBps —
        # normalize by their recorded cpu count so the guard line stays
        # continuous across the metric change.
        value = arm.get("MBps_per_core")
        if value is None and arm.get("MBps") is not None:
            cpus = (data.get("config", {}) or {}).get("cpus") or 1
            value = float(arm["MBps"]) / max(int(cpus), 1)
        if value is None:
            continue
        n = int(m.group(1))
        if not best or n > best["round"]:
            best = {
                "round": n,
                "value": float(value),
                "file": os.path.basename(path),
            }
    return best


class _Origin:
    """Deterministic synthetic origin: piece N of a url is a seeded
    numpy byte block (fast to generate, digest-stable)."""

    def __init__(self, piece_size: int, n_pieces: int) -> None:
        self.piece_size = piece_size
        self.n_pieces = n_pieces

    def content(self, url: str, number: int) -> bytes:
        size = self.piece_size
        if number == self.n_pieces - 1:
            size = self.piece_size  # equal-size pieces keep sums trivial
        seed = (hash(url) ^ (number * 2654435761)) & 0x7FFFFFFF
        rng = np.random.default_rng(seed)
        return rng.integers(0, 256, size, dtype=np.uint8).tobytes()

    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        return self.content(url, number)

    def content_length(self, url: str) -> int:
        # Length probe (conductor.probe_content_length): the proxy's
        # ranged/streamed opens size the task before the swarm runs.
        return self.piece_size * self.n_pieces


class _TimingFetcher:
    """PieceFetcher wrapper recording per-piece fetch wall times."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self.latencies: List[float] = []

    def fetch(self, *a, **kw):
        t0 = time.perf_counter()
        data = self.inner.fetch(*a, **kw)
        self.latencies.append(time.perf_counter() - t0)
        return data

    def piece_bitmap(self, *a, **kw):
        return self.inner.piece_bitmap(*a, **kw)

    def wait_piece_bitmap(self, *a, **kw):
        return self.inner.wait_piece_bitmap(*a, **kw)

    def native_endpoint(self, *a, **kw):
        # The conductor's native fetch window (§28) dials parents
        # directly — those pieces never pass through fetch() above.
        return self.inner.native_endpoint(*a, **kw)


class _Node:
    """One bench 'machine': piece server + remote scheduler client +
    conductor, configured as the legacy or the pipelined data plane.

    ``engine="native"`` runs the C++ piece store AND serves through the
    in-engine HTTP server (no Python on the serve path); the Python
    reference server stays on the legacy arm regardless.
    """

    def __init__(
        self,
        name: str,
        scheduler_url: str,
        root: str,
        origin,
        *,
        pipelined: bool,
        parallelism: int,
        engine: str = "py",
        stream_tee_depth: int = 0,
        native_fetch: bool = False,
    ) -> None:
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
        from dragonfly2_tpu.rpc.piece_transport import (
            PieceHTTPServer,
            make_piece_server,
        )
        from dragonfly2_tpu.scheduler.resource import Host

        native = engine == "native" and pipelined
        self.storage = DaemonStorage(
            os.path.join(root, name), prefer_native=native
        )
        if native and not self.storage.is_native:
            raise RuntimeError("--engine native: C++ engine did not build")
        self.upload = UploadManager(self.storage, concurrent_limit=64)
        if native:
            self.server = make_piece_server(self.upload)
        else:
            self.server = PieceHTTPServer(self.upload, use_sendfile=pipelined)
        self.server.serve()
        # Zero-disk-read evidence for the stream scenario: count engine
        # piece reads (the tee arm's fast path must not take any).
        self.piece_reads = 0
        eng = self.storage.engine
        orig_read = eng.read_piece

        def counting_read(*a, **kw):
            self.piece_reads += 1
            return orig_read(*a, **kw)

        eng.read_piece = counting_read
        self.host = Host(
            id=name, hostname=name, ip="127.0.0.1",
            download_port=self.server.port,
        )
        self.host.stats.network.idc = "idc-a"
        self.client = RemoteScheduler(scheduler_url)
        self.fetcher = _TimingFetcher(
            HTTPPieceFetcher(self.client.resolve_host, pooled=pipelined)
        )
        self.conductor = Conductor(
            self.host,
            self.storage,
            self.client,
            piece_fetcher=self.fetcher,
            source_fetcher=origin,
            piece_parallelism=parallelism,
            pipeline_depth=4 if pipelined else 0,
            batch_reports=pipelined,
            hedge_enabled=False,
            stream_tee_depth=stream_tee_depth,
            # Explicit per-arm: only the nativeboth arm runs the §28
            # in-engine fetch window; pipelined stays the Python
            # reference client even over the native server.
            native_fetch=native_fetch,
        )

    def stop(self) -> None:
        self.server.stop()
        self.fetcher.inner.close()
        self.storage.close()


class _StreamFacade:
    """The slice of the Daemon surface P2PProxy drives (open_stream +
    conductor) — the bench's edge node is a bare conductor."""

    def __init__(self, conductor) -> None:
        self.conductor = conductor

    def open_stream(self, url: str, **kw):
        return self.conductor.open_stream(url, **kw)


def _summarize(
    nbytes: int, wall: float, latencies: List[float],
    pieces: Optional[int] = None,
) -> dict:
    """Per-arm stats; ``pieces`` overrides the latency-sample count for
    arms whose per-piece walls live in-engine (the nativeboth arm's
    fetches never cross the Python timing wrapper — its p50/p99 report
    0 and ``pieces`` comes from the download results)."""
    lat = np.sort(np.asarray(latencies)) if latencies else np.asarray([0.0])
    total = len(lat)
    mbps = nbytes / max(wall, 1e-9) / 1e6
    return {
        "MBps": round(mbps, 1),
        "MBps_per_core": round(mbps / max(os.cpu_count() or 1, 1), 1),
        "p50_ms": round(float(lat[int(total * 0.50)]) * 1e3, 3),
        "p99_ms": round(float(lat[min(int(total * 0.99), total - 1)]) * 1e3, 3),
        "pieces": len(latencies) if pieces is None else pieces,
        "bytes": nbytes,
        "wall_s": round(wall, 4),
    }


def run(
    piece_size: int,
    n_pieces: int,
    rounds: int,
    swarm_n: int,
    parallelism: int,
    seed: int = 0,
    *,
    stream_consumers: int = 3,
    engine: str = "py",
) -> dict:
    from dragonfly2_tpu.daemon.proxy import P2PProxy, ProxyRouter, ProxyRule
    from dragonfly2_tpu.records.storage import Storage
    from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
    from dragonfly2_tpu.scheduler import (
        Evaluator,
        NetworkTopology,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
    )

    root = tempfile.mkdtemp(prefix="bench_dl_")
    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(os.path.join(root, "records"), buffer_size=256),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()

    origin = _Origin(piece_size, n_pieces)
    content_length = piece_size * n_pieces
    # native-both (§28): the native server backs the pipelined/stream
    # arms (as --engine native) AND a third arm moves the client inner
    # loop in-engine; the saturate scenario runs on both fast arms.
    native_both = engine == "native-both"
    server_engine = "native" if engine in ("native", "native-both") else "py"
    arms = ("legacy", "pipelined") + (("nativeboth",) if native_both else ())
    saturate_arms = ("pipelined", "nativeboth") if native_both else ()
    # One seed + clients per arm, reused across rounds (fresh task ids
    # per round keep the piece plane cold; node setup stays untimed).
    nodes: Dict[str, dict] = {}
    for arm in arms:
        pipelined = arm != "legacy"
        native_fetch = arm == "nativeboth"
        nodes[arm] = {
            "seed": _Node(
                f"seed-{arm}", server.url, root, origin,
                pipelined=pipelined, parallelism=parallelism,
                engine=server_engine, native_fetch=native_fetch,
            ),
            "clients": [
                _Node(
                    f"client-{arm}-{i}", server.url, root, origin,
                    pipelined=pipelined, parallelism=parallelism,
                    engine=server_engine, native_fetch=native_fetch,
                )
                for i in range(swarm_n)
            ],
        }

    # Pass-through stream plane (DESIGN.md §25): one shared seed, one
    # EDGE node per arm (identical pipelined data plane; the arms differ
    # ONLY in the read plane — tee vs disk round-trip) each fronted by a
    # real dfdaemon proxy that N HTTP consumers drain concurrently.
    stream_arms = ("stream_disk", "stream_tee")
    stream_seed = _Node(
        "stream-seed", server.url, root, origin,
        pipelined=True, parallelism=parallelism, engine=server_engine,
    )
    stream_nodes: Dict[str, dict] = {}
    for arm in stream_arms:
        edge = _Node(
            f"edge-{arm}", server.url, root, origin,
            pipelined=True, parallelism=parallelism, engine=server_engine,
            stream_tee_depth=8 if arm == "stream_tee" else 0,
        )
        proxy = P2PProxy(
            _StreamFacade(edge.conductor),
            ProxyRouter([ProxyRule.compile(r"^http://bench\.origin/")]),
            piece_size=piece_size,
        )
        proxy.serve()
        stream_nodes[arm] = {"edge": edge, "proxy": proxy}

    walls = {f"{arm}_{scen}": 0.0 for arm in arms for scen in ("single", "swarm")}
    walls.update({f"{arm}_saturate": 0.0 for arm in saturate_arms})
    walls.update(dict.fromkeys(stream_arms, 0.0))
    nbytes = dict.fromkeys(walls, 0)
    lats: Dict[str, List[float]] = {k: [] for k in walls}
    pieces_done = dict.fromkeys(walls, 0)
    stream_disk_reads = dict.fromkeys(stream_arms, 0)

    import zlib

    _crc_cache: Dict[str, int] = {}

    def _origin_crc(url: str) -> int:
        if url not in _crc_cache:
            crc = 0
            for n in range(n_pieces):
                crc = zlib.crc32(origin.content(url, n), crc)
            _crc_cache[url] = crc
        return _crc_cache[url]

    def _crc_check(storage, task_id: str, url: str, arm: str) -> None:
        """Digest discipline (§28): every measured download hands back
        the ORIGIN's bytes, every arm, every round — checked OUTSIDE the
        timed wall."""
        got = zlib.crc32(storage.read_task_bytes(task_id))
        if got != _origin_crc(url):
            raise RuntimeError(f"{arm}: downloaded bytes fail crc vs origin")

    def _seed_task(arm: str, url: str) -> None:
        r = nodes[arm]["seed"].conductor.download(
            url, piece_size=piece_size, content_length=content_length
        )
        if not (r.ok and r.pieces == n_pieces):
            raise RuntimeError(f"seeding failed: {r}")

    def _measure_single(arm: str, url: str) -> None:
        client = nodes[arm]["clients"][0]
        n0 = len(client.fetcher.latencies)
        t0 = time.perf_counter()
        r = client.conductor.download(url, piece_size=piece_size)
        wall = time.perf_counter() - t0
        if not (r.ok and not r.back_to_source and r.bytes == content_length):
            raise RuntimeError(f"single download ({arm}) fell off p2p: {r}")
        _crc_check(client.storage, r.task_id, url, arm)
        key = f"{arm}_single"
        walls[key] += wall
        nbytes[key] += r.bytes
        lats[key].extend(client.fetcher.latencies[n0:])
        pieces_done[key] += r.pieces
        client.storage.delete_task(r.task_id)

    def _measure_saturate(arm: str, urls: List[str]) -> None:
        """Saturate the box: every client pulls a DISTINCT task from the
        arm's seed concurrently — aggregate throughput with no
        inter-client piece sharing; wall is first-start → last-finish."""
        clients = nodes[arm]["clients"]
        marks = [len(c.fetcher.latencies) for c in clients]
        spans = [(0.0, 0.0)] * len(clients)
        results: List = [None] * len(clients)

        def worker(i: int) -> None:
            t0 = time.perf_counter()
            results[i] = clients[i].conductor.download(
                urls[i], piece_size=piece_size
            )
            spans[i] = (t0, time.perf_counter())

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(len(clients))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        key = f"{arm}_saturate"
        for i, r in enumerate(results):
            if r is None or not r.ok or r.back_to_source:
                raise RuntimeError(f"saturate download ({arm}) failed: {r}")
            _crc_check(clients[i].storage, r.task_id, urls[i], arm)
            nbytes[key] += r.bytes
            pieces_done[key] += r.pieces
            lats[key].extend(clients[i].fetcher.latencies[marks[i]:])
            clients[i].storage.delete_task(r.task_id)
        walls[key] += max(s[1] for s in spans) - min(s[0] for s in spans)

    def _measure_swarm(arm: str, url: str) -> None:
        clients = nodes[arm]["clients"]
        marks = [len(c.fetcher.latencies) for c in clients]
        spans = [(0.0, 0.0)] * len(clients)
        results: List = [None] * len(clients)

        def worker(i: int) -> None:
            t0 = time.perf_counter()
            results[i] = clients[i].conductor.download(
                url, piece_size=piece_size
            )
            spans[i] = (t0, time.perf_counter())

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(len(clients))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = 0
        for i, r in enumerate(results):
            if r is None or not r.ok or r.back_to_source:
                raise RuntimeError(f"swarm download ({arm}) failed: {r}")
            total += r.bytes
            lats[f"{arm}_swarm"].extend(clients[i].fetcher.latencies[marks[i]:])
            pieces_done[f"{arm}_swarm"] += r.pieces
            clients[i].storage.delete_task(r.task_id)
        wall = max(s[1] for s in spans) - min(s[0] for s in spans)
        walls[f"{arm}_swarm"] += wall
        nbytes[f"{arm}_swarm"] += total

    def _measure_stream(arm: str, url: str, *, measured: bool) -> None:
        """N concurrent HTTP consumers drain the task through the proxy
        WHILE the edge node's P2P download runs; the arm's wall is the
        slowest consumer's time-to-last-byte."""
        import urllib.request
        import zlib

        edge = stream_nodes[arm]["edge"]
        proxy = stream_nodes[arm]["proxy"]
        reads_before = edge.piece_reads
        ttlbs = [0.0] * stream_consumers
        got = [0] * stream_consumers
        crcs = [0] * stream_consumers
        errors: List[str] = []

        def consume(i: int) -> None:
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{proxy.port}/{url}"
                )
                crc = 0
                with urllib.request.urlopen(req, timeout=120) as resp:
                    while True:
                        chunk = resp.read(1 << 16)
                        if not chunk:
                            break
                        got[i] += len(chunk)
                        crc = zlib.crc32(chunk, crc)
                crcs[i] = crc
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(f"consumer {i}: {exc}")
            ttlbs[i] = time.perf_counter() - t0

        threads = [
            threading.Thread(target=consume, args=(i,), daemon=True)
            for i in range(stream_consumers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors or any(g != content_length for g in got):
            raise RuntimeError(
                f"stream ({arm}) failed: {errors or got}"
            )
        # Digest discipline: every consumer must hand the client the
        # ORIGIN's bytes (tee == disk == origin), every round.
        expected_crc = 0
        for n in range(n_pieces):
            expected_crc = zlib.crc32(origin.content(url, n), expected_crc)
        if any(c != expected_crc for c in crcs):
            raise RuntimeError(f"stream ({arm}) served corrupted bytes")
        edge_tid = edge.conductor._task_id(url, None)
        r = edge.conductor.active_run(edge_tid)
        if r is not None:
            r.wait_done(30.0)
        if measured:
            walls[arm] += max(ttlbs)
            nbytes[arm] += sum(got)
            lats[arm].extend(ttlbs)
            stream_disk_reads[arm] += edge.piece_reads - reads_before
        edge.storage.delete_task(edge_tid)

    try:
        for r in range(rounds + 1):
            measured = r > 0
            if r == 1:
                gc.collect()
                gc.disable()
            for arm in arms:
                url_single = f"bench://dl-{seed}-{arm}-single-{r}"
                url_swarm = f"bench://dl-{seed}-{arm}-swarm-{r}"
                _seed_task(arm, url_single)
                _seed_task(arm, url_swarm)
                # Warm pass (r == 0) runs the same code path; everything
                # recorded is zeroed at the end of the warm round.
                _measure_single(arm, url_single)
                _measure_swarm(arm, url_swarm)
                nodes[arm]["seed"].storage.delete_task(
                    nodes[arm]["seed"].conductor._task_id(url_single, None)
                )
                nodes[arm]["seed"].storage.delete_task(
                    nodes[arm]["seed"].conductor._task_id(url_swarm, None)
                )
            for arm in saturate_arms:
                sat_urls = [
                    f"bench://dl-{seed}-{arm}-sat-{r}-{i}"
                    for i in range(swarm_n)
                ]
                for u in sat_urls:
                    _seed_task(arm, u)
                _measure_saturate(arm, sat_urls)
                for u in sat_urls:
                    nodes[arm]["seed"].storage.delete_task(
                        nodes[arm]["seed"].conductor._task_id(u, None)
                    )
            if not measured:
                for k in walls:
                    walls[k] = 0.0
                    nbytes[k] = 0
                    lats[k].clear()
                    pieces_done[k] = 0
            for arm in stream_arms:
                url_stream = f"http://bench.origin/dl-{seed}-{arm}-{r}"
                res = stream_seed.conductor.download(
                    url_stream, piece_size=piece_size,
                    content_length=content_length,
                )
                if not (res.ok and res.pieces == n_pieces):
                    raise RuntimeError(f"stream seeding failed: {res}")
                _measure_stream(arm, url_stream, measured=measured)
                stream_seed.storage.delete_task(
                    stream_seed.conductor._task_id(url_stream, None)
                )
        pool_stats = {
            "dials": sum(
                c.fetcher.inner.pool.dials for c in nodes["pipelined"]["clients"]
            ),
            "reuses": sum(
                c.fetcher.inner.pool.reuses for c in nodes["pipelined"]["clients"]
            ),
        }
        serve_stats = {
            "engine": engine,
            "sendfile_serves": getattr(
                nodes["pipelined"]["seed"].server, "sendfile_serves", 0
            )
            + sum(
                getattr(c.server, "sendfile_serves", 0)
                for c in nodes["pipelined"]["clients"]
            ),
            "legacy_sendfile_serves": getattr(
                nodes["legacy"]["seed"].server, "sendfile_serves", 0
            ),
            # In-engine serve accounting (ps_serve_stats2) when the
            # native server carried the pipelined arms.
            "native_serves": sum(
                getattr(n.server, "upload_count", 0)
                for n in [nodes["pipelined"]["seed"], stream_seed]
                + nodes["pipelined"]["clients"]
            ) if server_engine == "native" else 0,
            # Coalesced-burst evidence (§28 batched submission): pieces
            # the native servers answered through one writev burst —
            # nonzero proves the client-side pipelining actually
            # triggered server-side batching.
            "batched_pieces": sum(
                getattr(nd.server, "batched_pieces", 0)
                for arm in arms
                for nd in [nodes[arm]["seed"]] + nodes[arm]["clients"]
            ) if server_engine == "native" else 0,
        }
        from dragonfly2_tpu.daemon.piece_pipeline import STREAM_TEE_TOTAL

        stream_stats = {
            "consumers": stream_consumers,
            # Engine piece reads on the edge node during measured stream
            # rounds: the tee arm's zero-disk-read evidence (spills and
            # late-attach pieces are the only legal nonzero sources).
            "disk_reads_tee": stream_disk_reads["stream_tee"],
            "disk_reads_disk": stream_disk_reads["stream_disk"],
            "tee_delivered": int(STREAM_TEE_TOTAL.value(outcome="delivered")),
            "tee_spilled": int(STREAM_TEE_TOTAL.value(outcome="spilled")),
        }
    finally:
        gc.enable()
        for arm in arms:
            nodes[arm]["seed"].stop()
            for c in nodes[arm]["clients"]:
                c.stop()
        for arm in stream_arms:
            stream_nodes[arm]["proxy"].stop()
            stream_nodes[arm]["edge"].stop()
        stream_seed.stop()
        server.stop()
        shutil.rmtree(root, ignore_errors=True)

    # Teardown leak assert (§28 flaky-surface fix): every native server
    # must have stopped cleanly — a wedged data-plane connection used to
    # be a stderr print, now it fails the bench by name.
    from dragonfly2_tpu import native as native_mod

    leaked = native_mod.leaked_servers()
    if server_engine == "native" and any(leaked):
        raise RuntimeError(
            f"native teardown leaked servers/conns: {leaked} (ps_leak_stats)"
        )

    arms_out = {
        k: _summarize(
            nbytes[k], walls[k], lats[k],
            pieces=None if k in stream_arms else pieces_done[k],
        )
        for k in walls
    }
    out = {
        "ok": True,
        "metric": "download_MBps",
        "config": {
            "piece_size": piece_size,
            "n_pieces": n_pieces,
            "content_mb": round(content_length / 1e6, 2),
            "rounds": rounds,
            "swarm_clients": swarm_n,
            "piece_parallelism": parallelism,
            "stream_consumers": stream_consumers,
            "engine": engine,
            "seed": seed,
            "cpus": os.cpu_count(),
        },
        "arms": arms_out,
        "speedup_single": round(
            arms_out["pipelined_single"]["MBps"]
            / max(arms_out["legacy_single"]["MBps"], 1e-9),
            2,
        ),
        "speedup_swarm": round(
            arms_out["pipelined_swarm"]["MBps"]
            / max(arms_out["legacy_swarm"]["MBps"], 1e-9),
            2,
        ),
        # Time-to-last-byte ratio for the pass-through stream: bytes are
        # identical, so the MB/s ratio IS the TTLB ratio (disk ÷ tee).
        "speedup_stream": round(
            arms_out["stream_tee"]["MBps"]
            / max(arms_out["stream_disk"]["MBps"], 1e-9),
            2,
        ),
        "pool": pool_stats,
        "serve": serve_stats,
        "stream": stream_stats,
        # §28 client-side plane: per-core speedups of the in-engine
        # fetch loop vs the pipelined-Python reference client (same
        # denominator, so the per-core ratio IS the MB/s ratio — kept
        # per-core so the headline transfers to multi-core boxes).
        "native": {
            "enabled": native_both,
            "leaked_servers": list(leaked),
            "speedup_native_single": round(
                arms_out["nativeboth_single"]["MBps_per_core"]
                / max(arms_out["pipelined_single"]["MBps_per_core"], 1e-9),
                2,
            ) if native_both else None,
            "speedup_native_saturate": round(
                arms_out["nativeboth_saturate"]["MBps_per_core"]
                / max(arms_out["pipelined_saturate"]["MBps_per_core"], 1e-9),
                2,
            ) if native_both else None,
        },
    }
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--piece-mb", type=float, default=4.0,
                   help="piece size in MiB (daemon default: 4)")
    p.add_argument("--pieces", type=int, default=16)
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved measured rounds (+1 unmeasured warm)")
    p.add_argument("--swarm", type=int, default=3,
                   help="concurrent clients in the swarm scenario")
    p.add_argument("--parallelism", type=int, default=4,
                   help="piece workers per download (both arms)")
    p.add_argument("--stream-consumers", type=int, default=3,
                   help="concurrent proxy consumers in the stream scenario")
    p.add_argument("--engine", choices=("py", "native", "native-both"),
                   default="py",
                   help="piece store/server for the pipelined+stream arms "
                        "(native = the C++ in-engine server; native-both "
                        "adds the in-engine CLIENT fetch loop arm and the "
                        "saturate-the-box scenario, DESIGN.md §28)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.piece_mb, args.pieces = 0.0625, 4
        args.rounds, args.swarm, args.parallelism = 1, 2, 2
        args.stream_consumers = 2
    try:
        out = run(
            int(args.piece_mb * (1 << 20)), args.pieces, max(args.rounds, 1),
            max(args.swarm, 1), max(args.parallelism, 1), args.seed,
            stream_consumers=max(args.stream_consumers, 1),
            engine=args.engine,
        )
        missing = [k for k in SCHEMA_KEYS if k not in out]
        for arm, stats in out["arms"].items():
            missing += [f"{arm}.{k}" for k in ARM_KEYS if k not in stats]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
        # Regression guard (bench.py discipline) over the download
        # headline: single-peer pipelined MB/s PER CORE vs the last
        # recorded BENCH_DL_r*.json round (older rounds normalize by
        # their recorded cpu count in last_good_download).
        import bench

        guard = {"value": out["arms"]["pipelined_single"]["MBps_per_core"]}
        bench.apply_regression_guard(guard, last_good_download())
        out["last_good"] = guard.get("last_good", {})
        if "regression_warning" in guard:
            out["regression_warning"] = guard["regression_warning"]
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "download_MBps",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }, sort_keys=True))
        return 1
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
