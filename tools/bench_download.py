"""End-to-end download throughput benchmark: wall-clock MB/s through the
REAL piece data plane (scheduler RPC over HTTP + piece servers on
loopback sockets), single-peer and N-peer swarm.

Two arms per scenario, measured in INTERLEAVED rounds (bench_sched.py
discipline: one unmeasured warm round, GC quiesced, walls measured in
the downloading workers):

- ``legacy``    — the pre-PR-11 path kept as the reference: one fresh
  urllib connection per piece, whole-piece buffered serve, strictly
  sequential fetch→digest→commit→report per worker;
- ``pipelined`` — this PR's data plane: per-parent keep-alive connection
  pool, ``os.sendfile`` zero-copy serve, commit pipeline (digest piece N
  while N+1 is on the wire) and bounded-linger batched piece reports.

Hedging is OFF in both arms (it is a tail-latency feature; a loopback
bench would never trigger it and enabling it only on one arm would skew
the comparison).

Reports MB/s and p50/p99 per-piece fetch latency per arm, the
``speedup_single`` / ``speedup_swarm`` ratios (acceptance bar:
single ≥ 2×), pool reuse stats and server sendfile counts as evidence
the fast arm really exercised the new plane, and a regression guard over
``BENCH_DL_r*.json`` rounds at the repo root (bench.py's
``apply_regression_guard`` applied to the download headline).

Usage: PYTHONPATH=/root/repo python tools/bench_download.py
       [--piece-mb 4] [--pieces 16] [--rounds 3] [--swarm 3]
       [--parallelism 4] [--seed 0]
       [--smoke]   # tiny sizes: the tier-1 JSON-schema gate
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import re
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "arms",
    "speedup_single",
    "speedup_swarm",
    "pool",
    "serve",
)

ARM_KEYS = ("MBps", "p50_ms", "p99_ms", "pieces", "bytes", "wall_s")


def last_good_download(repo_dir: Optional[str] = None) -> dict:
    """Most recent BENCH_DL_r*.json with a parsed single-peer headline —
    the download plane's regression bar (bench.py discipline)."""
    repo_dir = repo_dir or str(Path(__file__).resolve().parents[1])
    best: dict = {}
    for path in glob.glob(os.path.join(repo_dir, "BENCH_DL_r*.json")):
        m = re.search(r"BENCH_DL_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        value = (data.get("arms", {}).get("pipelined_single") or {}).get("MBps")
        if value is None:
            continue
        n = int(m.group(1))
        if not best or n > best["round"]:
            best = {
                "round": n,
                "value": float(value),
                "file": os.path.basename(path),
            }
    return best


class _Origin:
    """Deterministic synthetic origin: piece N of a url is a seeded
    numpy byte block (fast to generate, digest-stable)."""

    def __init__(self, piece_size: int, n_pieces: int) -> None:
        self.piece_size = piece_size
        self.n_pieces = n_pieces

    def content(self, url: str, number: int) -> bytes:
        size = self.piece_size
        if number == self.n_pieces - 1:
            size = self.piece_size  # equal-size pieces keep sums trivial
        seed = (hash(url) ^ (number * 2654435761)) & 0x7FFFFFFF
        rng = np.random.default_rng(seed)
        return rng.integers(0, 256, size, dtype=np.uint8).tobytes()

    def fetch(self, url: str, number: int, piece_size: int) -> bytes:
        return self.content(url, number)


class _TimingFetcher:
    """PieceFetcher wrapper recording per-piece fetch wall times."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self.latencies: List[float] = []

    def fetch(self, *a, **kw):
        t0 = time.perf_counter()
        data = self.inner.fetch(*a, **kw)
        self.latencies.append(time.perf_counter() - t0)
        return data

    def piece_bitmap(self, *a, **kw):
        return self.inner.piece_bitmap(*a, **kw)

    def wait_piece_bitmap(self, *a, **kw):
        return self.inner.wait_piece_bitmap(*a, **kw)


class _Node:
    """One bench 'machine': piece server + remote scheduler client +
    conductor, configured as the legacy or the pipelined data plane."""

    def __init__(
        self,
        name: str,
        scheduler_url: str,
        root: str,
        origin,
        *,
        pipelined: bool,
        parallelism: int,
    ) -> None:
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
        from dragonfly2_tpu.rpc.piece_transport import PieceHTTPServer
        from dragonfly2_tpu.scheduler.resource import Host

        self.storage = DaemonStorage(
            os.path.join(root, name), prefer_native=False
        )
        self.upload = UploadManager(self.storage, concurrent_limit=64)
        self.server = PieceHTTPServer(self.upload, use_sendfile=pipelined)
        self.server.serve()
        self.host = Host(
            id=name, hostname=name, ip="127.0.0.1",
            download_port=self.server.port,
        )
        self.host.stats.network.idc = "idc-a"
        self.client = RemoteScheduler(scheduler_url)
        self.fetcher = _TimingFetcher(
            HTTPPieceFetcher(self.client.resolve_host, pooled=pipelined)
        )
        self.conductor = Conductor(
            self.host,
            self.storage,
            self.client,
            piece_fetcher=self.fetcher,
            source_fetcher=origin,
            piece_parallelism=parallelism,
            pipeline_depth=4 if pipelined else 0,
            batch_reports=pipelined,
            hedge_enabled=False,
        )

    def stop(self) -> None:
        self.server.stop()
        self.fetcher.inner.close()
        self.storage.close()


def _summarize(nbytes: int, wall: float, latencies: List[float]) -> dict:
    lat = np.sort(np.asarray(latencies)) if latencies else np.asarray([0.0])
    total = len(lat)
    return {
        "MBps": round(nbytes / max(wall, 1e-9) / 1e6, 1),
        "p50_ms": round(float(lat[int(total * 0.50)]) * 1e3, 3),
        "p99_ms": round(float(lat[min(int(total * 0.99), total - 1)]) * 1e3, 3),
        "pieces": total,
        "bytes": nbytes,
        "wall_s": round(wall, 4),
    }


def run(
    piece_size: int,
    n_pieces: int,
    rounds: int,
    swarm_n: int,
    parallelism: int,
    seed: int = 0,
) -> dict:
    from dragonfly2_tpu.records.storage import Storage
    from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
    from dragonfly2_tpu.scheduler import (
        Evaluator,
        NetworkTopology,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
    )

    root = tempfile.mkdtemp(prefix="bench_dl_")
    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(os.path.join(root, "records"), buffer_size=256),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()

    origin = _Origin(piece_size, n_pieces)
    content_length = piece_size * n_pieces
    arms = ("legacy", "pipelined")
    # One seed + clients per arm, reused across rounds (fresh task ids
    # per round keep the piece plane cold; node setup stays untimed).
    nodes: Dict[str, dict] = {}
    for arm in arms:
        pipelined = arm == "pipelined"
        nodes[arm] = {
            "seed": _Node(
                f"seed-{arm}", server.url, root, origin,
                pipelined=pipelined, parallelism=parallelism,
            ),
            "clients": [
                _Node(
                    f"client-{arm}-{i}", server.url, root, origin,
                    pipelined=pipelined, parallelism=parallelism,
                )
                for i in range(swarm_n)
            ],
        }

    walls = {f"{arm}_{scen}": 0.0 for arm in arms for scen in ("single", "swarm")}
    nbytes = dict.fromkeys(walls, 0)
    lats: Dict[str, List[float]] = {k: [] for k in walls}

    def _seed_task(arm: str, url: str) -> None:
        r = nodes[arm]["seed"].conductor.download(
            url, piece_size=piece_size, content_length=content_length
        )
        if not (r.ok and r.pieces == n_pieces):
            raise RuntimeError(f"seeding failed: {r}")

    def _measure_single(arm: str, url: str) -> None:
        client = nodes[arm]["clients"][0]
        n0 = len(client.fetcher.latencies)
        t0 = time.perf_counter()
        r = client.conductor.download(url, piece_size=piece_size)
        wall = time.perf_counter() - t0
        if not (r.ok and not r.back_to_source and r.bytes == content_length):
            raise RuntimeError(f"single download ({arm}) fell off p2p: {r}")
        key = f"{arm}_single"
        walls[key] += wall
        nbytes[key] += r.bytes
        lats[key].extend(client.fetcher.latencies[n0:])
        client.storage.delete_task(r.task_id)

    def _measure_swarm(arm: str, url: str) -> None:
        clients = nodes[arm]["clients"]
        marks = [len(c.fetcher.latencies) for c in clients]
        spans = [(0.0, 0.0)] * len(clients)
        results: List = [None] * len(clients)

        def worker(i: int) -> None:
            t0 = time.perf_counter()
            results[i] = clients[i].conductor.download(
                url, piece_size=piece_size
            )
            spans[i] = (t0, time.perf_counter())

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(len(clients))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = 0
        for i, r in enumerate(results):
            if r is None or not r.ok or r.back_to_source:
                raise RuntimeError(f"swarm download ({arm}) failed: {r}")
            total += r.bytes
            lats[f"{arm}_swarm"].extend(clients[i].fetcher.latencies[marks[i]:])
            clients[i].storage.delete_task(r.task_id)
        wall = max(s[1] for s in spans) - min(s[0] for s in spans)
        walls[f"{arm}_swarm"] += wall
        nbytes[f"{arm}_swarm"] += total

    try:
        for r in range(rounds + 1):
            measured = r > 0
            if r == 1:
                gc.collect()
                gc.disable()
            for arm in arms:
                url_single = f"bench://dl-{seed}-{arm}-single-{r}"
                url_swarm = f"bench://dl-{seed}-{arm}-swarm-{r}"
                _seed_task(arm, url_single)
                _seed_task(arm, url_swarm)
                if measured:
                    _measure_single(arm, url_single)
                    _measure_swarm(arm, url_swarm)
                else:
                    # Warm pass: same code path, nothing recorded.
                    _measure_single(arm, url_single)
                    _measure_swarm(arm, url_swarm)
                    for k in walls:
                        walls[k] = 0.0
                        nbytes[k] = 0
                        lats[k].clear()
                nodes[arm]["seed"].storage.delete_task(
                    nodes[arm]["seed"].conductor._task_id(url_single, None)
                )
                nodes[arm]["seed"].storage.delete_task(
                    nodes[arm]["seed"].conductor._task_id(url_swarm, None)
                )
        pool_stats = {
            "dials": sum(
                c.fetcher.inner.pool.dials for c in nodes["pipelined"]["clients"]
            ),
            "reuses": sum(
                c.fetcher.inner.pool.reuses for c in nodes["pipelined"]["clients"]
            ),
        }
        serve_stats = {
            "sendfile_serves": nodes["pipelined"]["seed"].server.sendfile_serves
            + sum(
                c.server.sendfile_serves for c in nodes["pipelined"]["clients"]
            ),
            "legacy_sendfile_serves": nodes["legacy"]["seed"].server.sendfile_serves,
        }
    finally:
        gc.enable()
        for arm in arms:
            nodes[arm]["seed"].stop()
            for c in nodes[arm]["clients"]:
                c.stop()
        server.stop()
        shutil.rmtree(root, ignore_errors=True)

    arms_out = {k: _summarize(nbytes[k], walls[k], lats[k]) for k in walls}
    out = {
        "ok": True,
        "metric": "download_MBps",
        "config": {
            "piece_size": piece_size,
            "n_pieces": n_pieces,
            "content_mb": round(content_length / 1e6, 2),
            "rounds": rounds,
            "swarm_clients": swarm_n,
            "piece_parallelism": parallelism,
            "seed": seed,
            "cpus": os.cpu_count(),
        },
        "arms": arms_out,
        "speedup_single": round(
            arms_out["pipelined_single"]["MBps"]
            / max(arms_out["legacy_single"]["MBps"], 1e-9),
            2,
        ),
        "speedup_swarm": round(
            arms_out["pipelined_swarm"]["MBps"]
            / max(arms_out["legacy_swarm"]["MBps"], 1e-9),
            2,
        ),
        "pool": pool_stats,
        "serve": serve_stats,
    }
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--piece-mb", type=float, default=4.0,
                   help="piece size in MiB (daemon default: 4)")
    p.add_argument("--pieces", type=int, default=16)
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved measured rounds (+1 unmeasured warm)")
    p.add_argument("--swarm", type=int, default=3,
                   help="concurrent clients in the swarm scenario")
    p.add_argument("--parallelism", type=int, default=4,
                   help="piece workers per download (both arms)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.piece_mb, args.pieces = 0.0625, 4
        args.rounds, args.swarm, args.parallelism = 1, 2, 2
    try:
        out = run(
            int(args.piece_mb * (1 << 20)), args.pieces, max(args.rounds, 1),
            max(args.swarm, 1), max(args.parallelism, 1), args.seed,
        )
        missing = [k for k in SCHEMA_KEYS if k not in out]
        for arm, stats in out["arms"].items():
            missing += [f"{arm}.{k}" for k in ARM_KEYS if k not in stats]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
        # Regression guard (bench.py discipline) over the download
        # headline: single-peer pipelined MB/s vs the last recorded
        # BENCH_DL_r*.json round.
        import bench

        guard = {"value": out["arms"]["pipelined_single"]["MBps"]}
        bench.apply_regression_guard(guard, last_good_download())
        out["last_good"] = guard.get("last_good", {})
        if "regression_warning" in guard:
            out["regression_warning"] = guard["regression_warning"]
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "download_MBps",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
