"""Piece data-plane throughput: native C++ server vs Python HTTP server.

Loopback, 4 MiB pieces, 8 concurrent fetchers (the VERDICT r1 bar:
>= 2 GB/s aggregate).  Two client flavors:

- ``http``: the production HTTPPieceFetcher (urllib; one connection per
  piece — includes client-side Python costs);
- ``raw``: persistent-connection socket clients reading into a
  reusable buffer — measures the SERVER's ceiling.

Usage: PYTHONPATH=/root/repo python tools/bench_pieces.py
Prints one JSON line per (server, client) combination.
"""

from __future__ import annotations

import json
import socket
import sys
import tempfile
import threading
import time

PIECE = 4 << 20
N_PIECES = 32
N_FETCHERS = 8
ROUNDS = 6  # each fetcher reads the whole task this many times


RAW_WORKER = r"""
import socket, sys
port, task_id, rounds, n_pieces = (
    int(sys.argv[1]), sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
)
sock = socket.create_connection(("127.0.0.1", port))
sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
f = sock.makefile("rb", buffering=1 << 20)
buf = bytearray(1 << 20)
view = memoryview(buf)
total = 0
for r in range(rounds):
    for n in range(n_pieces):
        sock.sendall(
            f"GET /pieces/{task_id}/{n} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
        )
        cl = 0
        while True:
            line = f.readline()
            if not line or line == b"\r\n":
                break
            if line.lower().startswith(b"content-length:"):
                cl = int(line.split(b":")[1])
        remaining = cl
        while remaining > 0:
            k = f.readinto(view[: min(len(buf), remaining)])
            if not k:
                raise RuntimeError("short read")
            remaining -= k
        total += cl
sock.close()
print(total)
"""


def http_worker(fetcher, host_id, task_id, stats, idx) -> None:
    total = 0
    for r in range(ROUNDS):
        for n in range(N_PIECES):
            total += len(fetcher.fetch(host_id, task_id, n))
    stats[idx] = total


def bench(server_kind: str, client_kind: str, tmp: str) -> dict:
    from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
    from dragonfly2_tpu.rpc.piece_transport import (
        HTTPPieceFetcher,
        NativePieceServer,
        PieceHTTPServer,
    )
    from dragonfly2_tpu.utils import idgen

    storage = DaemonStorage(
        f"{tmp}/{server_kind}-{client_kind}",
        prefer_native=(server_kind == "native"),
    )
    upload = UploadManager(storage, concurrent_limit=64)
    task_id = idgen.task_id(f"https://origin/bench-{server_kind}")
    storage.register_task(task_id, piece_size=PIECE,
                          content_length=N_PIECES * PIECE)
    blob = bytes(range(256)) * (PIECE // 256)
    for n in range(N_PIECES):
        storage.write_piece(task_id, n, blob)

    if server_kind == "native":
        server = NativePieceServer(upload)
    else:
        server = PieceHTTPServer(upload)
        server.serve()
    port = server.port

    import resource

    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    if client_kind == "raw":
        # One PROCESS per fetcher (real peers are separate processes; a
        # shared client GIL would measure the benchmark, not the server).
        import subprocess

        procs = [
            subprocess.Popen(
                [sys.executable, "-c", RAW_WORKER, str(port), task_id,
                 str(ROUNDS), str(N_PIECES)],
                stdout=subprocess.PIPE, text=True,
            )
            for _ in range(N_FETCHERS)
        ]
        stats = [int(p.communicate()[0]) for p in procs]
    else:
        stats = [0] * N_FETCHERS
        threads = []
        for i in range(N_FETCHERS):
            fetcher = HTTPPieceFetcher(lambda hid: ("127.0.0.1", port))
            t = threading.Thread(target=http_worker,
                                 args=(fetcher, "h", task_id, stats, i))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    wall = time.perf_counter() - t0
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    server.stop()
    total_gb = sum(stats) / 1e9
    # Server-side CPU burned per GB served (the server runs in THIS
    # process; raw clients are separate processes).  On a 1-core sandbox
    # the wall-clock aggregate measures the whole copy chain including
    # clients — GB per server-core-second is the hardware-independent
    # capability figure.
    server_cpu = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
    out = {
        "server": server_kind,
        "client": client_kind,
        "aggregate_GBps": round(total_gb / wall, 2),
        "total_GB": round(total_gb, 1),
        "wall_s": round(wall, 2),
        "fetchers": N_FETCHERS,
    }
    if client_kind == "raw":
        out["server_cpu_s"] = round(server_cpu, 2)
        out["GB_per_server_core_s"] = round(total_gb / max(server_cpu, 1e-9), 2)
    return out


def main() -> None:
    from dragonfly2_tpu import native

    tmp = tempfile.mkdtemp()
    # (python, raw) is omitted: the Python server closes per request
    # (HTTP/1.0) and the persistent raw client targets keep-alive servers.
    combos = [("python", "http")]
    if native.available():
        combos += [("native", "http"), ("native", "raw")]
    else:
        print(f"# native unavailable: {native.build_error()}", file=sys.stderr)
    for server_kind, client_kind in combos:
        print(json.dumps(bench(server_kind, client_kind, tmp)), flush=True)


if __name__ == "__main__":
    main()
