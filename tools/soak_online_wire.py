"""Online graph training fed through the REAL wire, on the chip.

The 1B online soak (tools/soak_online_1b.py) proves the training loop
at scale with in-process feeds; tools/bench_wire_ingest.py proves the
Train stream moves bytes faster than training consumes them.  This tool
composes the two END TO END with no shortcuts in between:

  producer ──HTTP Train stream (DFC1 chunks)──► TrainerService
      (online_sink) ──StreamingRowDecoder──► WireIngestAdapter
      ──bounded queues──► OnlineGraphTrainer (TPU) ── snapshot refreshes
      from WIRE-fed topology shards

Both record types ride the wire: download chunks continuously, a probe
sweep per epoch.  Every ``--refresh-every`` dispatches the trainer
rebuilds its graph from the wire-fed window (hop tables hot-swap,
optimizer untouched).  The sustained rate is HONESTLY producer-bound
(~1.5M rows/s of numpy generation; wire ~4M rec/s and the train step
~4.8M rec/s are each measured faster in BENCHMARKS.md) — the point is
that the composed pipeline holds the north-star consumption rate
(1.3M records/s) with every hop real.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python tools/soak_online_wire.py \\
      [--records 2e8] [--nodes 100000] [--hidden 1024]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def _producer_proc(
    base_url: str, session_id: str, nodes: int, block_rows: int,
    total: int, rows_per_epoch: int, idx: int = 0, n_producers: int = 1,
) -> None:
    """Runs in its own PROCESS: generate the drifting world's records and
    stream both dataset kinds to the trainer's wire."""
    import urllib.request

    from dragonfly2_tpu.records.columnar import ColumnarHeader, _encode_header
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS, TOPO_COLUMNS
    from dragonfly2_tpu.records.synthetic import SyntheticCluster

    cluster = SyntheticCluster(num_hosts=nodes, seed=0)
    buckets = cluster._bucket_table()
    header = _encode_header(ColumnarHeader(columns=DOWNLOAD_COLUMNS))
    seqs: dict = {}

    def post(kind: str, name: str, payload: bytes) -> None:
        seq = seqs.get(name, 0)
        req = urllib.request.Request(
            f"{base_url}/train/shard?session={session_id}&kind={kind}"
            f"&name={name}&seq={seq}",
            data=payload, method="POST",
        )
        urllib.request.urlopen(req, timeout=600).close()
        seqs[name] = seq + 1

    def probe_shard(epoch: int) -> bytes:
        rng = np.random.default_rng(88_000 + epoch)
        n = nodes * 16
        src = rng.integers(0, nodes, n)
        dst = rng.integers(0, nodes, n)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        rows = np.zeros((len(src), len(TOPO_COLUMNS)), np.float32)
        rows[:, 0] = buckets[src]
        rows[:, 1] = buckets[dst]
        rows[:, 2] = (cluster._rtt_vec(src, dst, rng=rng) / 1e9).astype(
            np.float32
        )
        return _encode_header(
            ColumnarHeader(columns=TOPO_COLUMNS)
        ) + rows.tobytes()

    # Producer i takes global blocks i, i+P, i+2P, … — many producers,
    # one stream (the deployment shape: several schedulers upload to one
    # trainer).  Only producer 0 ships the topology sweeps.
    epoch = -1
    n_blocks = (total + block_rows - 1) // block_rows
    for g in range(idx, n_blocks, n_producers):
        offset = g * block_rows
        e = offset // rows_per_epoch
        if e != epoch:
            while epoch < e:
                epoch += 1
                if epoch > 0:
                    cluster.drift(np.random.default_rng(77_000 + epoch))
            if idx == 0:
                post("networktopology", f"topo-{epoch}", probe_shard(epoch))
        n = min(block_rows, total - offset)
        rows = cluster.generate_feature_rows(n, seed=10_000 + g)
        name = f"dl-{epoch}-p{idx}"
        payload = (header if seqs.get(name, 0) == 0 else b"")
        post("download", name, payload + rows.tobytes())


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=float, default=2e8)
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=131_072)
    ap.add_argument("--super", dest="super_steps", type=int, default=8)
    ap.add_argument("--refresh-every", type=int, default=0,
                    help="dispatches per snapshot refresh (0 = auto: 3 swaps)")
    ap.add_argument("--block-rows", type=int, default=1_000_000)
    ap.add_argument("--producers", type=int, default=4)
    ap.add_argument("--stage-dir", default="/dev/shm",
                    help="staging parent (tmpfs isolates the sandbox disk)")
    args = ap.parse_args()

    import tempfile

    from dragonfly2_tpu.models.hop import HopConfig
    from dragonfly2_tpu.records.columnar import _encode_header, ColumnarHeader
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS, TOPO_COLUMNS
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.rpc.trainer_transport import (
        RemoteTrainer,
        TrainerHTTPServer,
    )
    from dragonfly2_tpu.trainer.online_graph import (
        OnlineGraphConfig,
        OnlineGraphTrainer,
    )
    from dragonfly2_tpu.trainer.service import TrainerService
    from dragonfly2_tpu.trainer.train import TrainConfig

    t_wall0 = time.time()
    rows_per_dispatch = args.batch * args.super_steps
    n_dispatch = int(np.ceil(args.records / rows_per_dispatch))
    R = args.refresh_every or max(n_dispatch // 4, 1)

    # Trainer on the chip, fed ONLY by the wire.
    cfg = OnlineGraphConfig(
        num_nodes=args.nodes,
        max_neighbors=16,
        batch_size=args.batch,
        super_steps=args.super_steps,
        refresh_every=R,
        topo_window=args.nodes * 16,
        queue_capacity=4,
        model=HopConfig(hidden=args.hidden),
        train=TrainConfig(warmup_steps=50),
        total_steps_hint=n_dispatch * args.super_steps,
    )
    trainer = OnlineGraphTrainer(
        cfg,
        node_feats=np.zeros((args.nodes, 12), np.float32),
        topo_src=np.zeros(0, np.int32), topo_dst=np.zeros(0, np.int32),
        topo_rtt=np.zeros(0, np.float32),
    )
    adapter = trainer.make_wire_adapter()
    stage = tempfile.mkdtemp(prefix="wire-soak-", dir=args.stage_dir)
    service = TrainerService(data_dir=stage, online_sink=adapter)
    service._run_training = lambda run, session: run.done.set()
    server = TrainerHTTPServer(service)
    server.serve()
    client = RemoteTrainer(server.url, timeout=600)
    session = client.open_train_stream(
        ip="10.9.9.9", hostname="wire-soak", scheduler_id="soak"
    )


    # The producer runs in its OWN process (the deployment shape: the
    # scheduler generating/uploading datasets is never the trainer's
    # process) — HTTP is already the boundary, so only the server URL,
    # session id, and scale parameters cross.
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    prods = [
        ctx.Process(
            target=_producer_proc,
            args=(server.url, session._session_id, args.nodes,
                  args.block_rows, int(n_dispatch * rows_per_dispatch),
                  R * rows_per_dispatch, i, args.producers),
            daemon=True,
        )
        for i in range(args.producers)
    ]
    for pr in prods:
        pr.start()

    def watch_producer() -> None:
        for pr in prods:
            pr.join()
        trainer.end_of_stream()

    threading.Thread(target=watch_producer, daemon=True).start()

    # Snapshot 0 comes OFF THE WIRE: wait for the producer's first probe
    # sweep to land, then build the first real graph before training.
    deadline = time.time() + 120
    while trainer._fed_since_swap == 0 and time.time() < deadline:
        time.sleep(0.1)
    assert trainer.refresh_snapshot() is not None, "no wire topology arrived"
    print(f"wire-soak: snapshot from wire topology "
          f"({len(trainer._window[0])} probe edges)", flush=True)

    t0 = time.time()
    d = 0
    last = t0
    while d < n_dispatch:
        ran = trainer.run(max_dispatches=1, idle_timeout=60.0)
        if ran == 0:
            break
        d += 1
        now = time.time()
        if now - last > 15 or d == n_dispatch:
            rate = trainer.records_seen / (now - t0)
            fed = sum(service._online_fed.values())
            print(f"wire-soak: dispatch {d}/{n_dispatch} "
                  f"({trainer.records_seen / 1e6:.0f}M trained, "
                  f"{fed / 1e6:.0f}M rows off the wire, "
                  f"snapshot={trainer.snapshot_idx}) "
                  f"sustained={rate / 1e6:.2f}M rec/s", flush=True)
            last = now
    train_s = time.time() - t0
    for pr in prods:
        if pr.is_alive():
            pr.terminate()
    server.stop()
    final_overflow = adapter.overflow_edges
    trainer.close()  # release the native ingest engine's buffers

    import shutil

    shutil.rmtree(stage, ignore_errors=True)
    fed = sum(service._online_fed.values())
    row_bytes = 4 * len(DOWNLOAD_COLUMNS)
    print(json.dumps({
        "records_trained": trainer.records_seen,
        "rows_off_the_wire": fed,
        "dispatches": d,
        "snapshots": trainer.snapshot_idx,
        "overflow_edges": final_overflow,
        "train_s": round(train_s, 1),
        "wall_s": round(time.time() - t_wall0, 1),
        "records_per_s_sustained": round(trainer.records_seen / train_s, 1),
        "payload_MBps": round(trainer.records_seen * row_bytes / train_s / 1e6, 1),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
