"""BASELINE configs[2] ablation: GAT vs hop-feature ranker vs plain MLP.

Same workload for every model — 100k-node probe graph, 2M download
edges, log1p-bandwidth targets, identical split — so the comparison is
apples-to-apples:

- ``gat``  — GATRanker (models/gnn.py), the round-1 flagship;
- ``hop``  — HopRanker (models/hop.py), precomputed aggregation;
- ``mlp``  — MLPRegressor on endpoint HOST FEATURES only (no graph, no
  node identity): the ablation VERDICT r1 weak-#7 asked for — how much
  does the graph actually buy?

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python tools/ablate_rankers.py [gat|hop|mlp ...]
Prints one JSON line per model.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from dragonfly2_tpu.models import build_neighbor_table
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.train import (
        TrainConfig,
        train_gat_ranker,
        train_hop_ranker,
    )

    which = [a for a in sys.argv[1:] if not a.startswith("-")] or ["hop", "mlp"]
    on_tpu = jax.devices()[0].platform != "cpu"
    n_nodes = 100_000 if on_tpu else 2_000
    n_edges = 2_000_000 if on_tpu else 40_000
    epochs = 60 if on_tpu else 8

    print(f"# workload: {n_nodes} nodes, {n_edges} edges, {epochs} epochs",
          file=sys.stderr, flush=True)
    cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
    src, dst, rtt = cluster.probe_edges(density=16 / max(n_nodes - 1, 1), seed=0)
    table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=16)
    nf = cluster._host_feature_matrix()

    rng = np.random.default_rng(0)
    es = rng.integers(0, n_nodes, n_edges).astype(np.int32)
    ed = (es + rng.integers(1, n_nodes, n_edges).astype(np.int32)) % n_nodes
    y = np.log1p(cluster._bandwidth_vec(es, ed)).astype(np.float32)
    mean_mae = float(np.mean(np.abs(y - y.mean())))
    cfg = TrainConfig(epochs=epochs)

    def report(name, metrics, wall, extra=None):
        out = {
            "model": name,
            "val_log_mae": round(metrics.mae, 4),
            "f1": round(metrics.f1, 4),
            "mean_predictor_mae": round(mean_mae, 4),
            "wall_s": round(wall, 1),
        }
        out.update(extra or {})
        print(json.dumps(out), flush=True)

    if "hop" in which:
        t0 = time.time()
        _, m, hist = train_hop_ranker(nf, table, es, ed, y, config=cfg)
        report("hop", m, time.time() - t0,
               {"records_per_sec": round(hist[-1]["records_per_sec"], 1) if hist else None})

    if "gat" in which:
        t0 = time.time()
        _, m, hist = train_gat_ranker(nf, table, es, ed, y, config=cfg,
                                      batch_size=131_072)
        report("gat", m, time.time() - t0,
               {"records_per_sec": round(hist[-1]["records_per_sec"], 1) if hist else None})

    if "mlp" in which:
        # No graph, no node identity: endpoint host features only — the
        # graph-value ablation.  Small bespoke loop (train_mlp is coupled
        # to the download-record column layout).
        import jax.numpy as jnp
        import optax
        from dragonfly2_tpu.models import MLPConfig, MLPRegressor
        from dragonfly2_tpu.models.mlp import warm_start_output_bias
        from dragonfly2_tpu.trainer.train import (
            _huber, _regression_metrics,
        )

        feats = np.concatenate([nf[es], nf[ed]], axis=1).astype(np.float32)
        mu, sd = feats.mean(0), np.maximum(feats.std(0), 1e-3)
        feats = (feats - mu) / sd
        split = int(len(y) * 0.9)
        t0 = time.time()
        model = MLPRegressor(MLPConfig(in_dim=feats.shape[1], dropout=0.0))
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, feats.shape[1])))["params"]
        params = warm_start_output_bias(params, float(y[:split].mean()))
        tx = optax.adamw(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, o, xb, yb):
            def loss_fn(pp):
                return _huber(model.apply({"params": pp}, xb), yb)
            l, g = jax.value_and_grad(loss_fn)(p)
            up, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, up), o2, l

        b = 65_536
        for epoch in range(epochs):
            order = np.random.default_rng(epoch).permutation(split)
            for s0 in range(0, split - b + 1, b):
                idx = order[s0:s0 + b]
                params, opt, _ = step(
                    params, opt, jnp.asarray(feats[idx]), jnp.asarray(y[idx])
                )
        pred = np.asarray(model.apply({"params": params}, jnp.asarray(feats[split:])))
        report("mlp_hostfeats", _regression_metrics(pred, y[split:]), time.time() - t0)


if __name__ == "__main__":
    main()
