"""Profile the GAT train step on the real chip: where do the 99 ms go?

Chained-slope methodology (see bench.py): N sequentially-dependent
iterations inside one jit, scalar fetch, per-iter = slope between two
chain lengths.  Pitfalls this script works around:
- fetch must depend on EVERY carried leaf (XLA dead-tuple-element
  elimination deletes loop compute whose output isn't fetched);
- never multiply by literal 0 to build a dependency (constant-folded);
- relay variance ~±25%: reps, take min.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/profile_gat.py
"""

from __future__ import annotations

import sys
import time
from functools import partial

import numpy as np


def chain_time(fn, carry, n_short=4, n_long=16, reps=2):
    """fn(carry) -> carry (same pytree). Returns ms per call."""
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(1,))
    def run(c, n):
        def body(_, cc):
            return fn(cc)
        out = jax.lax.fori_loop(0, n, body, c)
        # Touch every float leaf so nothing in the loop is DCE'd.
        tot = jnp.float32(0)
        for leaf in jax.tree_util.tree_leaves(out):
            tot = tot + leaf.reshape(-1)[0].astype(jnp.float32)
        return tot

    float(run(carry, n_short))
    float(run(carry, n_long))
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(run(carry, n_short))
        ts = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run(carry, n_long))
        tl = time.perf_counter() - t0
        vals.append((tl - ts) / (n_long - n_short) * 1e3)
    return min(vals)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.models import GATRanker, GNNConfig, build_neighbor_table
    from dragonfly2_tpu.ops.transpose_gather import make_transpose_gather
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.train import (
        TrainConfig, TrainState, _graph_train_step, _make_optimizer,
    )

    on_tpu = jax.devices()[0].platform != "cpu"
    n_nodes = 100_000 if on_tpu else 4096
    batch = 131_072 if on_tpu else 8192
    K = 16
    D = 128
    only = sys.argv[1] if len(sys.argv) > 1 else ""

    print(f"building workload n={n_nodes} batch={batch}", flush=True)
    cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
    density = K / max(n_nodes - 1, 1)
    src, dst, rtt = cluster.probe_edges(density=density, seed=0)
    table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=K)
    node_feats = jnp.asarray(cluster._host_feature_matrix())

    rng = np.random.default_rng(0)
    e_src = rng.integers(0, n_nodes, batch).astype(np.int32)
    e_dst = (e_src + rng.integers(1, n_nodes, batch).astype(np.int32)) % n_nodes
    bw = cluster._bandwidth_vec(e_src, e_dst)
    target = jnp.asarray(np.log1p(bw).astype(np.float32))
    a, b = jnp.asarray(e_src), jnp.asarray(e_dst)
    cfg = TrainConfig()

    def make_state(gnn_cfg):
        model = GATRanker(gnn_cfg)
        params = model.init(
            jax.random.PRNGKey(0), node_feats, table, a[:2], b[:2]
        )["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params,
            tx=_make_optimizer(cfg, 100), dropout_rng=jax.random.PRNGKey(1),
        )

    results = {}

    def report(name, ms):
        results[name] = ms
        print(f"{name}: {ms:.1f} ms", flush=True)

    def full_step_probe(gnn_cfg):
        st = make_state(gnn_cfg)

        def step(s):
            new_s, _ = _graph_train_step(s, node_feats, table, a, b, target, None)
            return new_s
        return chain_time(step, st)

    # 1. baseline full train step
    if only in ("", "base"):
        report("full_train_step", full_step_probe(GNNConfig()))

    # 2. full train step with the scatter-free transpose gather
    if only in ("", "transpose"):
        t0 = time.perf_counter()
        tg = make_transpose_gather(
            np.asarray(table.indices), np.asarray(table.mask), n_nodes
        )
        print(f"  transpose table built in {time.perf_counter()-t0:.1f}s", flush=True)
        report("full_train_step_transpose", full_step_probe(GNNConfig(gather_fn=tg)))

    if only not in ("", "micro"):
        print(results)
        return

    # micro probes ---------------------------------------------------------
    h0 = jnp.full((n_nodes, D), 0.5, jnp.bfloat16)
    idx = table.indices

    def gather_fwd(h):
        g = jnp.take(h, idx, axis=0)
        return h + g.sum(axis=1) * jnp.bfloat16(1e-6)
    report("gather_fwd", chain_time(gather_fwd, h0))

    def gather_grad(h):
        def f(x):
            g = jnp.take(x, idx, axis=0)
            return (g.astype(jnp.float32) ** 2).sum() * 1e-9
        gr = jax.grad(f)(h)
        return h + gr.astype(h.dtype)
    report("gather_grad", chain_time(gather_grad, h0))

    # scatter-as-gather backward candidate, isolated
    from dragonfly2_tpu.ops.transpose_gather import build_transpose_table

    tt = build_transpose_table(np.asarray(idx), np.asarray(table.mask), n_nodes)
    print(f"  kout={tt.tidx.shape[1]} overflow={int(tt.over_pos.shape[0])}", flush=True)
    E = n_nodes * K
    ct0 = jnp.full((E, D), 0.25, jnp.bfloat16)
    has_spill = int(tt.over_pos.shape[0]) > 0

    def sag(ct):
        rows = jnp.take(ct, tt.tidx, axis=0)
        out = (rows * tt.tmask[..., None].astype(rows.dtype)).sum(axis=1)
        if has_spill:
            out = out.at[tt.over_dst].add(jnp.take(ct, tt.over_pos, axis=0))
        return ct + out.reshape(-1)[0] * jnp.bfloat16(1e-6)
    report("scatter_as_gather", chain_time(sag, ct0))

    # XLA segment-sum (the sort-based scatter the backward uses)
    seg_ids = jnp.asarray(np.asarray(idx).reshape(-1).astype(np.int32))

    def xla_seg(ct):
        out = jax.ops.segment_sum(
            ct.astype(jnp.float32), seg_ids, num_segments=n_nodes
        )
        return ct + out.reshape(-1)[0].astype(ct.dtype) * jnp.bfloat16(1e-6)
    report("xla_segment_sum", chain_time(xla_seg, ct0))

    # per-edge matmuls [E,D]x[D,D] x2 (the k/v denses, forward)
    w0 = jnp.full((D, D), 0.01, jnp.bfloat16)

    def edge_matmul(c):
        v, w = c
        o1 = v @ w
        o2 = v @ w
        return (v + (o1 + o2) * jnp.bfloat16(1e-6), w)
    report("edge_matmuls_2x", chain_time(edge_matmul, (ct0, w0)))

    print(results)


if __name__ == "__main__":
    main()
