"""Merge per-process metric journals into fleet-wide telemetry.

Every plane appends crash-safe DFMJ1 snapshot frames to its own metric
journal (``--metric-journal`` / config ``telemetry.journal_path`` —
utils/metric_journal.py).  This tool is the metric twin of
``tools/trace_assemble.py``: it replays N processes' journals (torn
tails tolerated, digest-bad frames NEVER admitted) and answers the
operator's question the per-process ``/metrics`` scrape cannot — *what
is the swarm-wide piece-fetch p99 right now, and is it burning the SLO?*

  python tools/fleet_assemble.py JOURNAL [JOURNAL ...]
      [--json]                  # machine-readable full report
      [--quantiles 0.5,0.9,0.99]
      [--slo-config FILE]       # JSON list of SLO declarations
                                # (config telemetry.slos entries) to
                                # evaluate over the merged replay

Merge semantics (DESIGN.md §23):

- **sketches merge losslessly** — bucket counts add exactly, so the
  fleet quantile equals the quantile of one sketch that observed every
  process's samples (within the declared relative-error bound α);
- **counters sum with restart/reset detection via run identity** —
  snapshots are cumulative per ``run_id``, so each run contributes its
  final admitted value exactly once, and a restarted process (fresh
  run_id) starts a new summand instead of being mistaken for a reset;
- **gauges stay per-run** — summing them is meaningless, so the report
  lists each run's final value;
- **SLOs replay** — with ``--slo-config``, the merged snapshot streams
  rebuild the fleet-cumulative (good, total) series and the burn-rate
  engine evaluates it exactly as a live fleet engine would
  (utils/slo.py replay_fleet).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _label_str(label_names: List[str], key: List[str]) -> str:
    if not key:
        return "{}"
    inner = ",".join(f'{n}="{v}"' for n, v in zip(label_names, key))
    return "{" + inner + "}"


def load_journals(
    paths: List[str],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Replay every journal → (all admitted snapshots, per-journal stats)."""
    from dragonfly2_tpu.utils.metric_journal import replay_metric_journal

    snapshots: List[Dict[str, Any]] = []
    stats: List[Dict[str, Any]] = []
    for path in paths:
        snaps, st = replay_metric_journal(path)
        st = dict(
            st,
            path=str(path),
            services=sorted({str(s.get("service", "")) for s in snaps}),
            runs=sorted({str(s.get("run_id", ""))[:8] for s in snaps}),
        )
        stats.append(st)
        snapshots.extend(snaps)
    return snapshots, stats


def merge_runs(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet-wide merge of the final admitted snapshot of every run."""
    from dragonfly2_tpu.utils.metric_journal import final_snapshots_by_run
    from dragonfly2_tpu.utils.metrics import merge_sketch_states

    finals = final_snapshots_by_run(snapshots)
    counters: Dict[str, Dict[str, Any]] = {}
    gauges: Dict[str, List[Dict[str, Any]]] = {}
    sketches: Dict[str, Dict[str, Any]] = {}
    runs: List[Dict[str, Any]] = []
    for (service, run_id), snap in sorted(finals.items()):
        runs.append(
            {
                "service": service,
                "run_id": run_id,
                "pid": snap.get("pid"),
                "last_seq": snap.get("seq"),
                "last_ts": snap.get("ts"),
            }
        )
        for name, state in snap.get("metrics", {}).items():
            kind = state.get("type")
            labels = state.get("labels", [])
            if kind == "counter":
                acc = counters.setdefault(
                    name, {"labels": labels, "series": {}, "total": 0.0}
                )
                for key, value in state.get("series", []):
                    ls = _label_str(labels, key)
                    acc["series"][ls] = acc["series"].get(ls, 0.0) + value
                    acc["total"] += value
            elif kind == "gauge":
                for key, value in state.get("series", []):
                    gauges.setdefault(name, []).append(
                        {
                            "service": service,
                            "run_id": run_id[:8],
                            "labels": _label_str(labels, key),
                            "value": value,
                        }
                    )
            elif kind == "sketch":
                acc = sketches.setdefault(
                    name, {"labels": labels, "states": []}
                )
                acc["states"].extend(
                    st for _key, st in state.get("series", [])
                )
    merged_sketches: Dict[str, Dict[str, Any]] = {}
    for name, acc in sketches.items():
        merged_sketches[name] = {
            "labels": acc["labels"],
            "state": merge_sketch_states(acc["states"]),
        }
    return {
        "runs": runs,
        "counters": counters,
        "gauges": gauges,
        "sketches": merged_sketches,
    }


def fleet_quantiles(
    merged: Dict[str, Any], quantiles: List[float]
) -> Dict[str, Dict[str, Any]]:
    from dragonfly2_tpu.utils.metrics import sketch_state_quantile

    out: Dict[str, Dict[str, Any]] = {}
    for name, entry in merged["sketches"].items():
        st = entry["state"]
        row: Dict[str, Any] = {
            "count": st["total"],
            "sum": round(st["sum"], 9),
            "alpha": st["alpha"],
            "min": st["min"],
            "max": st["max"],
        }
        for q in quantiles:
            v = sketch_state_quantile(st, q)
            row[f"p{q * 100:g}"] = None if v is None else round(v, 9)
        out[name] = row
    return out


def build_report(
    paths: List[str],
    *,
    quantiles: Optional[List[float]] = None,
    slo_config: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    snapshots, stats = load_journals(paths)
    merged = merge_runs(snapshots)
    report: Dict[str, Any] = {
        "journals": stats,
        "total_frames": sum(s["frames"] for s in stats),
        "total_corrupt": sum(s["corrupt"] for s in stats),
        "runs": merged["runs"],
        "counters": merged["counters"],
        "gauges": merged["gauges"],
        "quantiles": fleet_quantiles(merged, quantiles or [0.5, 0.9, 0.99]),
    }
    if slo_config:
        from dragonfly2_tpu.utils.slo import replay_fleet

        engine = replay_fleet(snapshots, slo_config)
        report["slos"] = engine.state()["slos"]
    return report


def render_report(report: Dict[str, Any]) -> str:
    lines = [
        f"{len(report['journals'])} journal(s), "
        f"{report['total_frames']} frame(s) admitted, "
        f"{report['total_corrupt']} corrupt frame(s) REJECTED",
    ]
    for j in report["journals"]:
        frag = (
            f"- {j['path']}: {j['frames']} frame(s), "
            f"services={','.join(j['services']) or '—'}"
        )
        if j["corrupt"]:
            frag += f", {j['corrupt']} corrupt REJECTED"
        if j["torn_tail"]:
            frag += ", torn tail tolerated"
        lines.append(frag)
    lines.append("")
    lines.append(f"{len(report['runs'])} run(s) merged:")
    for r in report["runs"]:
        lines.append(
            f"- {r['service']} run {r['run_id'][:8]} "
            f"(pid {r['pid']}, {r['last_seq']} snapshot(s))"
        )
    if report["quantiles"]:
        lines += ["", "Fleet quantiles (sketches merged losslessly):", ""]
        header = sorted(
            {k for row in report["quantiles"].values() for k in row
             if k.startswith("p")}
        )
        lines.append("| metric | count | " + " | ".join(header) + " |")
        lines.append("| --- " * (2 + len(header)) + "|")
        for name, row in sorted(report["quantiles"].items()):
            cells = [
                f"{row[h] * 1e3:.2f} ms" if row.get(h) is not None else "—"
                for h in header
            ]
            lines.append(
                f"| {name} | {int(row['count'])} | " + " | ".join(cells) + " |"
            )
    if report["counters"]:
        lines += ["", "Fleet counters (summed per run identity):", ""]
        for name, acc in sorted(report["counters"].items()):
            lines.append(f"- {name}: {acc['total']:g}")
            for ls, v in sorted(acc["series"].items()):
                if ls != "{}":
                    lines.append(f"    {ls} {v:g}")
    for slo_state in report.get("slos", []):
        lines += [
            "",
            f"SLO {slo_state['name']}: "
            f"{'BREACHED' if slo_state['breached'] else 'ok'} "
            f"(burn fast {slo_state['burn_rate_fast']:.2f} / "
            f"slow {slo_state['burn_rate_slow']:.2f}, "
            f"threshold {slo_state['burn_threshold']:.2f})",
        ]
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python tools/fleet_assemble.py",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("journals", nargs="+", help="per-process metric journals")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    p.add_argument("--quantiles", default="0.5,0.9,0.99",
                   help="comma-separated quantiles for the fleet table")
    p.add_argument("--slo-config", default=None, metavar="FILE",
                   help="JSON list of SLO declarations (config "
                        "telemetry.slos entries) to replay-evaluate")
    args = p.parse_args(argv)

    slo_config = None
    if args.slo_config:
        slo_config = json.loads(Path(args.slo_config).read_text())
    report = build_report(
        args.journals,
        quantiles=[float(x) for x in args.quantiles.split(",") if x],
        slo_config=slo_config,
    )
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
