"""Multi-tenant QoS isolation benchmark (DESIGN.md §26).

Drives ``sim/qos.py``'s overload drill — a measured tenant-A workload
(announce loop + real downloads off a seed daemon) against a tenant-B
announce+download flood — in INTERLEAVED rounds (bench_sched
discipline: GC quiesced, identical config per round, arms inside one
round share one box state):

- ``baseline``  — tenant A alone;
- ``unshaped``  — the burst with tenant-blind admission and no caps
                  (documents the baseline interference);
- ``shaped``    — the burst with the QoS plane live (background class,
                  announce-rate cap, upload-bandwidth cap, per-tenant
                  accounting + noisy-first shedding).

Headline: **isolation_score = 100 − max(shaped movement of tenant A's
announce p99 and download TTLB, in %, floored at 0)** over the best
round — ≥ 90 means the <10% isolation bar held.  Regression-guarded
over ``BENCH_QOS_r*.json`` (bench.py's 20% tripwire).  The 1-CPU
caveats (BENCHMARKS.md): per-round variance is real (±10-20% on these
µs/ms-scale signals — the announce p99 can move NEGATIVE under load
because the flood keeps the core hot), which is why rounds are
interleaved and the best round is the headline, same as bench_swarm.

Usage: PYTHONPATH=/root/repo python tools/bench_qos.py
       [--rounds 3] [--announces 1200] [--downloads 10]
       [--pieces 8] [--piece-size 65536] [--b-threads 2] [--seed 7]
       [--smoke]   # tiny drill: the tier-1 JSON-schema gate
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "value",
    "config",
    "rounds",
    "best",
    "movement",
    "arms",
)

ARM_KEYS = (
    "a_announce_p99_ms",
    "a_ttlb_ms",
    "b_offered",
    "b_sheds",
    "b_throttled",
    "a_downloads_ok",
)


def last_good_qos(repo_dir: Optional[str] = None) -> dict:
    """Most recent BENCH_QOS_r*.json with a parsed isolation headline —
    the QoS regression bar (bench.py discipline)."""
    repo_dir = repo_dir or str(Path(__file__).resolve().parents[1])
    best: dict = {}
    for path in glob.glob(os.path.join(repo_dir, "BENCH_QOS_r*.json")):
        m = re.search(r"BENCH_QOS_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        value = data.get("value")
        if value is None:
            continue
        n = int(m.group(1))
        if not best or n > best["round"]:
            best = {
                "round": n,
                "value": float(value),
                "file": os.path.basename(path),
            }
    return best


def _isolation_score(movement: Dict[str, float]) -> float:
    """100 − the worst shaped movement (announce p99 / TTLB), floored at
    0 from below (a negative movement is no interference, not credit)."""
    worst = max(
        0.0,
        float(movement["shaped_announce_p99_pct"]),
        float(movement["shaped_ttlb_pct"]),
    )
    return round(max(0.0, 100.0 - worst), 2)


def run(args) -> Dict[str, object]:
    from dragonfly2_tpu.sim.qos import QoSDrillConfig, run_isolation_drill

    cfg = QoSDrillConfig(
        a_announces=args.announces,
        a_downloads=args.downloads,
        pieces_per_task=args.pieces,
        piece_size=args.piece_size,
        b_threads=args.b_threads,
        seed=args.seed,
    )
    rounds: List[Dict[str, object]] = []
    gc.collect()
    gc.disable()
    try:
        for _ in range(max(1, args.rounds)):
            rounds.append(run_isolation_drill(cfg))
    finally:
        gc.enable()
    scored = [
        (_isolation_score(r["movement"]), i) for i, r in enumerate(rounds)
    ]
    best_score, best_i = max(scored)
    best = rounds[best_i]
    # Every round must prove the flood actually ran and the shaped arm
    # actually shed/capped it — an idle flood is a vacuous isolation.
    for r in rounds:
        if r["unshaped"]["b_offered"] == 0:
            raise RuntimeError("tenant-B flood never ran in an unshaped arm")
        shaped = r["shaped"]
        if shaped["b_sheds"] + shaped["b_throttled"] == 0:
            raise RuntimeError("shaped arm never shed or capped the flood")
        if shaped["a_downloads_ok"] != args.downloads:
            raise RuntimeError(
                "tenant-A downloads failed under the shaped burst: "
                f"{shaped['a_downloads_ok']}/{args.downloads}"
            )
    return {
        "ok": True,
        "metric": "qos_isolation_score",
        "value": best_score,
        "config": {
            "rounds": args.rounds,
            "a_announces": cfg.a_announces,
            "a_downloads": cfg.a_downloads,
            "pieces_per_task": cfg.pieces_per_task,
            "piece_size": cfg.piece_size,
            "b_threads": cfg.b_threads,
            "b_announce_qps": cfg.b_announce_qps,
            "b_upload_rate": cfg.b_upload_rate,
            "seed": cfg.seed,
        },
        "rounds": [r["movement"] for r in rounds],
        "best": best["movement"],
        "movement": best["movement"],
        "arms": {
            "baseline": best["baseline"],
            "unshaped": best["unshaped"],
            "shaped": best["shaped"],
        },
        "unshaped_interference": {
            "announce_p99_pct": best["movement"]["unshaped_announce_p99_pct"],
            "ttlb_pct": best["movement"]["unshaped_ttlb_pct"],
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--announces", type=int, default=1200)
    p.add_argument("--downloads", type=int, default=10)
    p.add_argument("--pieces", type=int, default=8)
    p.add_argument("--piece-size", type=int, default=64 * 1024)
    p.add_argument("--b-threads", type=int, default=2)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--smoke", action="store_true",
                   help="tiny drill: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.rounds, args.announces, args.downloads = 1, 200, 3
        args.pieces, args.piece_size = 4, 16 * 1024
    try:
        out = run(args)
        missing = [k for k in SCHEMA_KEYS if k not in out]
        for arm, stats in out["arms"].items():
            missing += [f"{arm}.{k}" for k in ARM_KEYS if k not in stats]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
        import bench

        guard = {"value": out["value"]}
        bench.apply_regression_guard(guard, last_good_qos())
        out["last_good"] = guard.get("last_good", {})
        if "regression_warning" in guard:
            out["regression_warning"] = guard["regression_warning"]
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "qos_isolation_score",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
