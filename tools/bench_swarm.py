"""Fleet-swarm benchmark: aggregate announces/sec across scheduler
shards at 100k+ simulated peers on one box.

Drives the columnar swarm population (sim/fleet.py: slot-matrix peer
state, vectorized per-tick churn draws per idc class) against REAL
``SchedulerService`` shards — each with its own Resource, columnar host
store and ShardGuard behind one consistent-hash ring (DESIGN.md §24).

Two arms, measured in INTERLEAVED rounds (bench_sched.py discipline:
one unmeasured warm round, GC quiesced, identical seeded workload):

- ``shards_1`` — the whole population on ONE scheduler instance (the
  pre-§24 deployment shape);
- ``shards_N`` — the same population split across N instances by ring
  ownership (host announces pin to the host id's owner; task traffic to
  the task id's owner).

The headline — **aggregate announces/sec across shards in the N-shard
arm** — is the fleet-scale serving signal, regression-guarded against
the last ``BENCH_SW_r*.json`` round (bench.py's 20% tripwire).
``speedup_shards`` reports the N-vs-1 ratio HONESTLY: on a 1-CPU box
the announce row-fill is CPU-bound and O(1) per announce, so sharding
divides *state* (hosts/tasks per instance, bind-miss churn), not
cycles — expect ~1× wall-clock there, and real scaling only where
shards get their own cores/processes (the chaos drill proves the wire
protocol; BENCHMARKS.md documents the wall).

A mid-run membership drill rides every measured N-shard round: one
shard is removed at the halfway tick (ring bump → survivor handoff
sweeps → steering), and the round asserts the drill's downloads still
complete — the migration protocol is exercised under load, not only in
the chaos test.

Usage: PYTHONPATH=/root/repo python tools/bench_swarm.py
       [--peers 128000] [--shards 4] [--ticks 4] [--rounds 2]
       [--announce-rate 0.5] [--download-rate 0.0005]
       [--cache-hosts 65536] [--seed 7]
       [--smoke]   # tiny population: the tier-1 JSON-schema gate
"""

from __future__ import annotations

import argparse
import gc
import glob
import json
import os
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "arms",
    "speedup_shards",
    "peers",
    "unique_hosts",
    "membership_drill",
)

ARM_KEYS = (
    "announces_per_sec",
    "announces",
    "wall_s",
    "hosts_per_shard_max",
    "bind_misses",
    "downloads_ok",
    "downloads_failed",
    "sheds",
)


def last_good_swarm(repo_dir: Optional[str] = None) -> dict:
    """Most recent BENCH_SW_r*.json with a parsed aggregate headline —
    the fleet-swarm regression bar (bench.py discipline)."""
    repo_dir = repo_dir or str(Path(__file__).resolve().parents[1])
    best: dict = {}
    for path in glob.glob(os.path.join(repo_dir, "BENCH_SW_r*.json")):
        m = re.search(r"BENCH_SW_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        value = (data.get("arms", {}).get("sharded") or {}).get(
            "announces_per_sec"
        )
        if value is None:
            continue
        n = int(m.group(1))
        if not best or n > best["round"]:
            best = {
                "round": n,
                "value": float(value),
                "file": os.path.basename(path),
            }
    return best


def _run_arm(
    n_shards: int,
    *,
    peers: int,
    ticks: int,
    seed: int,
    announce_rate: float,
    download_rate: float,
    cache_hosts: int,
    drill: bool,
) -> Dict[str, object]:
    """One arm run: fresh seeded population + fleet, full tick loop.
    With ``drill`` (N-shard measured rounds), one member is removed at
    the halfway tick — handoff/steering runs under the measured load."""
    from dragonfly2_tpu.sim import (
        ColumnarPopulation,
        FleetConfig,
        FleetSwarmDriver,
        ShardedFleet,
    )

    cfg = FleetConfig(
        num_peers=peers,
        seed=seed,
        announce_rate=announce_rate,
        download_rate=download_rate,
    )
    pop = ColumnarPopulation(cfg)
    fleet = ShardedFleet(n_shards, feature_cache_hosts=cache_hosts)
    driver = FleetSwarmDriver(pop, fleet)
    drill_out: Dict[str, object] = {"ran": False}
    run_drill = drill and n_shards > 1 and ticks >= 4
    first = ticks // 2 if run_drill else ticks
    rep = driver.run(first)
    wall = float(rep["wall_s"])
    if run_drill:
        # Membership drill under load, in two bumps with workload in
        # between so the client-side stale-ring paths really run: one
        # member dies (first downloads hit the dead socket analog and
        # re-route), then a replacement joins (survivor handoff sweeps
        # mark the newcomer's keys; stale-ring downloads get the
        # REDIRECT steering answer and follow it).
        victim = sorted(fleet.shards)[-1]
        victim_tasks = len(fleet.shards[victim].service.resource.task_manager)
        kill_moved = fleet.kill(victim)
        ok_before = driver.downloads_ok
        mid = max(1, (ticks - first) // 2)
        rep = driver.run(mid)
        wall += float(rep["wall_s"])
        add_moved = fleet.add_shard("shard-replacement")
        rep = driver.run(ticks - first - mid)
        wall += float(rep["wall_s"])
        drill_out = {
            "ran": True,
            "victim": victim,
            "victim_tasks": victim_tasks,
            "kill_handoffs": kill_moved,
            "add_handoffs": add_moved,
            "handed_off_tasks": sum(add_moved.values()),
            "ring_version": fleet.ring.version,
            "downloads_after_kill": driver.downloads_ok - ok_before,
            "rehomed_tasks": driver.rehomed_tasks,
            "redirects_followed": sum(
                s.redirects_followed for s in fleet.shards.values()
            ),
        }
    stats = fleet.stats()
    shards = stats["shards"]
    return {
        "announces_per_sec": round(rep["announces_per_sec"], 1),
        "announces": int(stats["announces"]),
        "wall_s": round(wall, 3),
        "announce_wall_s": round(float(rep["announce_wall_s"]), 3),
        "hosts_per_shard_max": max(s["hosts"] for s in shards.values()),
        "bind_misses": sum(s["cache_misses"] for s in shards.values()),
        "downloads_ok": driver.downloads_ok,
        "downloads_failed": driver.downloads_failed,
        "rehomed_tasks": driver.rehomed_tasks,
        "sheds": driver.sheds,
        "unique_hosts": int(rep["unique_hosts"]),
        "online": int(rep["online"]),
        "drill": drill_out,
    }


def run(args) -> Dict[str, object]:
    arms = {"single": 1, "sharded": max(2, args.shards)}
    rounds: Dict[str, List[Dict[str, object]]] = {k: [] for k in arms}
    gc.collect()
    gc.disable()
    try:
        # One unmeasured warm round (tiny) + interleaved measured rounds:
        # machine-wide noise lands on both arms roughly equally.
        for name, n in arms.items():
            _run_arm(
                n, peers=max(2000, args.peers // 50), ticks=2,
                seed=args.seed, announce_rate=args.announce_rate,
                download_rate=args.download_rate,
                cache_hosts=args.cache_hosts, drill=False,
            )
        for _ in range(max(1, args.rounds)):
            for name, n in arms.items():
                rounds[name].append(
                    _run_arm(
                        n, peers=args.peers, ticks=args.ticks,
                        seed=args.seed, announce_rate=args.announce_rate,
                        download_rate=args.download_rate,
                        cache_hosts=args.cache_hosts, drill=True,
                    )
                )
    finally:
        gc.enable()

    def best(name: str) -> Dict[str, object]:
        return max(
            rounds[name], key=lambda r: r["announces_per_sec"]
        )

    single, sharded = best("single"), best("sharded")
    drill = next(
        (r["drill"] for r in rounds["sharded"] if r["drill"].get("ran")),
        {"ran": False},
    )
    return {
        "ok": True,
        "metric": "swarm_announces_per_sec",
        "config": {
            "peers": args.peers,
            "shards": arms["sharded"],
            "ticks": args.ticks,
            "rounds": args.rounds,
            "announce_rate": args.announce_rate,
            "download_rate": args.download_rate,
            "cache_hosts": args.cache_hosts,
            "seed": args.seed,
        },
        "arms": {"single": single, "sharded": sharded},
        "speedup_shards": round(
            float(sharded["announces_per_sec"])
            / max(float(single["announces_per_sec"]), 1e-9),
            3,
        ),
        "peers": args.peers,
        "unique_hosts": int(sharded["unique_hosts"]),
        "membership_drill": drill,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--peers", type=int, default=128_000)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--ticks", type=int, default=4)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--announce-rate", type=float, default=0.5)
    p.add_argument("--download-rate", type=float, default=0.0005)
    p.add_argument("--cache-hosts", type=int, default=65536)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--smoke", action="store_true",
                   help="tiny population: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.peers, args.ticks, args.rounds = 2500, 4, 1
        args.cache_hosts = 1024
        # Enough downloads that every shard owns tasks and the
        # membership drill's handoff path actually moves keys.
        args.download_rate = 0.02
    try:
        out = run(args)
        missing = [k for k in SCHEMA_KEYS if k not in out]
        for arm, stats in out["arms"].items():
            missing += [f"{arm}.{k}" for k in ARM_KEYS if k not in stats]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
        # The membership drill is part of the measured product: a round
        # where migration broke downloads is a FAILED round, whatever
        # the throughput said.
        drill = out["membership_drill"]
        if drill.get("ran") and out["arms"]["sharded"]["downloads_failed"]:
            raise RuntimeError(
                "downloads failed across the membership drill: "
                f"{out['arms']['sharded']['downloads_failed']}"
            )
        import bench

        guard = {"value": out["arms"]["sharded"]["announces_per_sec"]}
        bench.apply_regression_guard(guard, last_good_swarm())
        out["last_good"] = guard.get("last_good", {})
        if "regression_warning" in guard:
            out["regression_warning"] = guard["regression_warning"]
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "swarm_announces_per_sec",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
