"""Stitch per-process flight-recorder logs into end-to-end traces.

Every plane writes its own crash-safe trace log (``--trace-log`` /
config ``tracing.log_path`` — utils/tracing.py DurableSpanExporter).
One trace id follows a download across daemon → scheduler → manager via
the W3C ``traceparent`` header, so the logs of N processes hold the
N process-local shards of each trace.  This tool reassembles them and
answers the operator's question: *where did this download's 400 ms go?*

  python tools/trace_assemble.py LOG [LOG ...]
      [--trace-id HEX]         # pick a trace (default: most spans)
      [--json]                 # machine-readable full report
      [--validate]             # every replayed frame must validate
                               # against utils/otlp_trace_schema.json
      [--gap-ms 50]            # leaf-coverage gap threshold
      [--markdown FILE --update]   # rewrite FILE's marked block

What it computes, per assembled trace:

- **critical path** — from the latest-finishing root, repeatedly descend
  into the latest-finishing child: the chain of spans that bounded the
  trace's wall clock (announce → schedule → piece fetches → commit);
- **per-phase latency breakdown** — spans bucketed by name prefix
  (announce / schedule / piece / source / commit / eval / manager /
  train / other), with count, total and max duration, and the share of
  the trace wall;
- **gaps** — intervals inside the trace extent covered by NO leaf span
  (nobody was doing attributable work: poll waits, lost wakeups,
  unexported spans of a killed process);
- **anomalies** — orphan spans (parent id present but the parent span
  missing: a crashed process never exported it — the expected SIGKILL
  signature), error-status spans, children starting before their parent
  (cross-process clock skew), plus per-log corrupt-frame counts.

Torn tails are tolerated exactly as the exporter's framing promises: a
SIGKILL mid-append costs at most the unfinished tail frame; digest-bad
frames are counted and NEVER admitted.

``--markdown FILE --update`` renders the summary between markers (the
``tools/bench_report.py`` discipline)::

    <!-- trace:assembly:begin --> ... <!-- trace:assembly:end -->
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

ASSEMBLY_BEGIN = "<!-- trace:assembly:begin -->"
ASSEMBLY_END = "<!-- trace:assembly:end -->"

# Span-name prefix → phase of the download story.  Order matters: first
# match wins.
PHASE_RULES: Tuple[Tuple[str, str], ...] = (
    ("rpc/announce_host", "announce"),
    ("rpc/register_peer", "schedule"),
    ("rpc/report_piece_failed", "schedule"),
    ("rpc/report_piece_finished", "commit"),
    ("rpc/report_pieces_finished", "commit"),
    ("rpc/report_peer_finished", "commit"),
    ("rpc/", "rpc"),
    ("daemon/source.piece", "source"),
    # The PR-11 data-plane spans, split so the per-download table reads
    # piece-fetch vs commit vs report-flush instead of one blob:
    # ``daemon/piece`` is the fetch wall (wire + hedge), the scheduler's
    # report_piece(s)_finished handlers are the commit acknowledgment,
    # and ``daemon/report.flush`` is the batched-report RPC window.
    ("daemon/report.flush", "report_flush"),
    ("daemon/piece", "piece"),
    ("daemon/pex-worker", "piece"),
    ("daemon/download", "download"),
    ("scheduler/eval", "eval"),
    ("manager/replicate", "replicate"),
    ("manager/", "manager"),
    ("jobs/", "jobs"),
    ("rollout/", "rollout"),
    ("trainer/", "train"),
)


def phase_of(name: str) -> str:
    for prefix, phase in PHASE_RULES:
        if name.startswith(prefix):
            return phase
    return "other"


def _span_ns(raw: Dict[str, Any], key: str) -> int:
    try:
        return int(raw.get(key, 0))
    except (TypeError, ValueError):
        return 0


def _attrs_of(raw: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for kv in raw.get("attributes", []):
        v = kv.get("value", {})
        if "intValue" in v:
            try:
                out[kv["key"]] = int(v["intValue"])
            except (TypeError, ValueError):
                out[kv["key"]] = v["intValue"]
        elif "doubleValue" in v:
            out[kv["key"]] = v["doubleValue"]
        elif "boolValue" in v:
            out[kv["key"]] = v["boolValue"]
        else:
            out[kv["key"]] = v.get("stringValue", "")
    return out


def load_logs(
    paths: List[str], *, validate: bool = False
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Replay every log → (spans, per-log stats).  With ``validate``,
    each admitted frame must pass the vendored OTLP schema (raises on
    the first violation — the chaos drill's "every durable span batch
    validates" bar)."""
    from dragonfly2_tpu.utils.tracing import log_spans, replay_trace_log

    validator = None
    if validate:
        import jsonschema

        from dragonfly2_tpu.utils.tracing import otlp_trace_schema

        validator = jsonschema.Draft202012Validator(otlp_trace_schema())

    spans: List[Dict[str, Any]] = []
    log_stats: List[Dict[str, Any]] = []
    for path in paths:
        requests, stats = replay_trace_log(path)
        if validator is not None:
            for req in requests:
                validator.validate(req)
        stats = dict(stats, path=str(path))
        log_stats.append(stats)
        for raw in log_spans(requests):
            spans.append(
                {
                    "trace_id": raw.get("traceId", ""),
                    "span_id": raw.get("spanId", ""),
                    "parent_id": raw.get("parentSpanId"),
                    "name": raw.get("name", ""),
                    "service": raw.get("service", ""),
                    "start_ns": _span_ns(raw, "startTimeUnixNano"),
                    "end_ns": _span_ns(raw, "endTimeUnixNano"),
                    "status": (raw.get("status") or {}).get("code", 1),
                    "status_message": (raw.get("status") or {}).get("message", ""),
                    "attrs": _attrs_of(raw),
                }
            )
    return spans, log_stats


def assemble(spans: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    traces: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for s in spans:
        traces[s["trace_id"]].append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: (s["start_ns"], s["end_ns"]))
    return dict(traces)


def critical_path(trace_spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Latest-finishing root, then repeatedly the latest-finishing child:
    the span chain that bounded the trace's wall clock.  Orphans (parent
    missing — e.g. a SIGKILLed process never exported it) count as
    roots, so a torn trace still renders a path."""
    by_id = {s["span_id"]: s for s in trace_spans}
    children: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    roots: List[Dict[str, Any]] = []
    for s in trace_spans:
        pid = s["parent_id"]
        if pid and pid in by_id:
            children[pid].append(s)
        else:
            roots.append(s)
    if not roots:
        return []
    path = [max(roots, key=lambda s: s["end_ns"])]
    while True:
        kids = children.get(path[-1]["span_id"])
        if not kids:
            return path
        path.append(max(kids, key=lambda s: s["end_ns"]))


def leaf_gaps(
    trace_spans: List[Dict[str, Any]], *, threshold_ns: int
) -> List[Dict[str, float]]:
    """Intervals inside the trace extent covered by NO leaf span: time
    where no attributable work ran (poll waits, stalls, or spans a dead
    process never exported)."""
    has_children = {
        s["parent_id"] for s in trace_spans if s["parent_id"]
    }
    leaves = [s for s in trace_spans if s["span_id"] not in has_children]
    if not leaves:
        return []
    t0 = min(s["start_ns"] for s in trace_spans)
    t1 = max(s["end_ns"] for s in trace_spans)
    intervals = sorted((s["start_ns"], s["end_ns"]) for s in leaves)
    gaps: List[Dict[str, float]] = []
    cursor = t0
    for start, end in intervals:
        if start - cursor >= threshold_ns:
            gaps.append(
                {
                    "start_ms": (cursor - t0) / 1e6,
                    "end_ms": (start - t0) / 1e6,
                    "duration_ms": (start - cursor) / 1e6,
                }
            )
        cursor = max(cursor, end)
    if t1 - cursor >= threshold_ns:
        gaps.append(
            {
                "start_ms": (cursor - t0) / 1e6,
                "end_ms": (t1 - t0) / 1e6,
                "duration_ms": (t1 - cursor) / 1e6,
            }
        )
    return gaps


def anomalies_of(trace_spans: List[Dict[str, Any]]) -> List[str]:
    by_id = {s["span_id"]: s for s in trace_spans}
    out: List[str] = []
    for s in trace_spans:
        pid = s["parent_id"]
        if pid and pid not in by_id:
            out.append(
                f"orphan span {s['name']} ({s['service']}): parent {pid[:8]}… "
                "missing — likely unexported by a crashed process"
            )
        elif pid and s["start_ns"] + 5_000_000 < by_id[pid]["start_ns"]:
            out.append(
                f"span {s['name']} starts {(by_id[pid]['start_ns'] - s['start_ns']) / 1e6:.1f} ms "
                f"before its parent {by_id[pid]['name']} — cross-process clock skew"
            )
        if s["status"] == 2:
            out.append(
                f"error span {s['name']} ({s['service']}): {s['status_message']}"
            )
    return out


def summarize_trace(
    trace_id: str, trace_spans: List[Dict[str, Any]], *, gap_ms: float = 50.0
) -> Dict[str, Any]:
    t0 = min(s["start_ns"] for s in trace_spans)
    t1 = max(s["end_ns"] for s in trace_spans)
    wall_ms = (t1 - t0) / 1e6
    phases: Dict[str, Dict[str, float]] = {}
    for s in trace_spans:
        p = phases.setdefault(
            phase_of(s["name"]), {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur = (s["end_ns"] - s["start_ns"]) / 1e6
        p["count"] += 1
        p["total_ms"] = round(p["total_ms"] + dur, 3)
        p["max_ms"] = round(max(p["max_ms"], dur), 3)
    if wall_ms > 0:
        for p in phases.values():
            p["pct_of_wall"] = round(100.0 * p["total_ms"] / wall_ms, 1)
    path = [
        {
            "name": s["name"],
            "service": s["service"],
            "start_ms": round((s["start_ns"] - t0) / 1e6, 3),
            "duration_ms": round((s["end_ns"] - s["start_ns"]) / 1e6, 3),
            "attrs": s["attrs"],
        }
        for s in critical_path(trace_spans)
    ]
    return {
        "trace_id": trace_id,
        "spans": len(trace_spans),
        "services": sorted({s["service"] for s in trace_spans}),
        "wall_ms": round(wall_ms, 3),
        "phases": dict(sorted(phases.items())),
        "critical_path": path,
        "gaps": leaf_gaps(trace_spans, threshold_ns=int(gap_ms * 1e6)),
        "anomalies": anomalies_of(trace_spans),
    }


def build_report(
    paths: List[str],
    *,
    trace_id: Optional[str] = None,
    gap_ms: float = 50.0,
    validate: bool = False,
) -> Dict[str, Any]:
    spans, log_stats = load_logs(paths, validate=validate)
    traces = assemble(spans)
    report: Dict[str, Any] = {
        "logs": log_stats,
        "traces": len(traces),
        "total_spans": len(spans),
    }
    if not traces:
        return report
    if trace_id is None:
        trace_id = max(traces, key=lambda t: len(traces[t]))
    if trace_id not in traces:
        raise SystemExit(f"trace {trace_id!r} not found in the given logs")
    report["trace"] = summarize_trace(trace_id, traces[trace_id], gap_ms=gap_ms)
    return report


def render_report(report: Dict[str, Any]) -> str:
    """The marker-delimited markdown block (bench_report.py discipline)."""
    lines = [
        ASSEMBLY_BEGIN,
        "Generated by `python tools/trace_assemble.py` from per-process",
        "flight-recorder logs (utils/tracing.py DurableSpanExporter).",
        "",
    ]
    for log in report["logs"]:
        frag = f"- `{log['path']}`: {log['frames']} frame(s)"
        if log["corrupt"]:
            frag += f", {log['corrupt']} corrupt frame(s) REJECTED"
        if log["torn_tail"]:
            frag += ", torn tail tolerated"
        lines.append(frag)
    lines.append("")
    trace = report.get("trace")
    if trace is None:
        lines += ["No assembled traces.", ASSEMBLY_END]
        return "\n".join(lines)
    lines += [
        f"Trace `{trace['trace_id']}` — {trace['spans']} span(s) across "
        f"{', '.join(trace['services']) or 'unknown services'}; wall "
        f"{trace['wall_ms']:.1f} ms.",
        "",
        "| phase | spans | total | max | % of wall |",
        "| --- | --- | --- | --- | --- |",
    ]
    for phase, p in trace["phases"].items():
        lines.append(
            f"| {phase} | {p['count']} | {p['total_ms']:.1f} ms | "
            f"{p['max_ms']:.1f} ms | {p.get('pct_of_wall', 0):.1f}% |"
        )
    lines += ["", "Critical path:", ""]
    for i, hop in enumerate(trace["critical_path"]):
        pad = "  " * i
        lines.append(
            f"- {pad}`{hop['name']}` ({hop['service']}) "
            f"@{hop['start_ms']:.1f} ms, {hop['duration_ms']:.1f} ms"
        )
    if trace["gaps"]:
        lines += ["", "Gaps (no leaf span running):", ""]
        for g in trace["gaps"]:
            lines.append(
                f"- {g['start_ms']:.1f}–{g['end_ms']:.1f} ms "
                f"({g['duration_ms']:.1f} ms idle)"
            )
    if trace["anomalies"]:
        lines += ["", "Anomalies:", ""]
        for a in trace["anomalies"]:
            lines.append(f"- {a}")
    lines.append(ASSEMBLY_END)
    return "\n".join(lines)


def update_file(path: Path, rendered: str) -> bool:
    text = path.read_text(encoding="utf-8")
    begin = text.find(ASSEMBLY_BEGIN)
    end = text.find(ASSEMBLY_END)
    if begin < 0 or end < 0:
        raise SystemExit(
            f"{path}: assembly markers not found "
            f"({ASSEMBLY_BEGIN} ... {ASSEMBLY_END})"
        )
    new = text[:begin] + rendered + text[end + len(ASSEMBLY_END):]
    if new != text:
        path.write_text(new, encoding="utf-8")
        return True
    return False


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python tools/trace_assemble.py",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("logs", nargs="+", help="per-process trace log files")
    p.add_argument("--trace-id", default=None,
                   help="assemble this trace (default: the largest)")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    p.add_argument("--validate", action="store_true",
                   help="validate every admitted frame against the "
                        "vendored OTLP schema")
    p.add_argument("--gap-ms", type=float, default=50.0,
                   help="minimum uncovered interval reported as a gap")
    p.add_argument("--markdown", default=None, metavar="FILE",
                   help="markdown file carrying the marked block")
    p.add_argument("--update", action="store_true",
                   help="rewrite --markdown's marked block in place")
    args = p.parse_args(argv)

    report = build_report(
        args.logs, trace_id=args.trace_id, gap_ms=args.gap_ms,
        validate=args.validate,
    )
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    rendered = render_report(report)
    if args.markdown and args.update:
        changed = update_file(Path(args.markdown), rendered)
        print(
            f"{args.markdown}: trace assembly "
            + ("updated" if changed else "already current")
        )
        return 0
    print(rendered)
    return 0


if __name__ == "__main__":
    sys.exit(main())
