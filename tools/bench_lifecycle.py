"""Lifecycle-plane benchmark: records-in → ACTIVE-out loop latency.

Times the self-driving model lifecycle (DESIGN.md §29) end to end by
running the REAL zero-human drill (sim/lifecycle.py — StreamingTrainer
epochs, digest-checked registry artifacts, guardrailed rollout walks,
honest regret@k verdicts) and reading its per-stage walls:

- **records_to_active_s** — fresh records fed → trained candidate
  registered → SHADOW → CANARY → ACTIVE, fully unattended (stage 1);
- **regression_to_rollback_s** — inverted-head candidate registered →
  guardrail breach → rolled back with last-good still ACTIVE (stage 2);
- **bounce_resume_s** — manager bounce mid-promotion → resumed plane
  promotes the surviving candidate to exactly one ACTIVE (stage 3);
- **records_per_sec** — training-records throughput over stage 1's
  ingest+train+promote wall (the loop's feed-side budget).

Prints ONE JSON line.  ``--smoke`` shrinks every size for the tier-1
schema gate (tests/test_lifecycle.py runs it in a subprocess).

Usage: PYTHONPATH=/root/repo python tools/bench_lifecycle.py
       [--epoch-records 512 --batch-size 64 --announces 80 --parents 6]
       [--seed 11] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "stages",
    "records_to_active_s",
    "regression_to_rollback_s",
    "bounce_resume_s",
    "records_per_sec",
    "drill_ok",
)


def run(epoch_records: int, batch_size: int, announces: int, parents: int,
        min_samples: int, seed: int) -> dict:
    from dragonfly2_tpu.sim.lifecycle import (
        LifecycleDrillConfig,
        run_lifecycle_drill,
    )

    cfg = LifecycleDrillConfig(
        seed=seed,
        epoch_records=epoch_records,
        batch_size=batch_size,
        announces=announces,
        parents=parents,
        min_shadow_samples=min_samples,
        min_canary_samples=min_samples,
    )
    out = run_lifecycle_drill(cfg)
    s1 = out["stage1"]
    fed = epoch_records + batch_size
    wall1 = float(s1["wall_s"]) or 1e-9
    return {
        "ok": True,
        "metric": "lifecycle_records_to_active_seconds",
        "config": {
            "epoch_records": epoch_records,
            "batch_size": batch_size,
            "announces": announces,
            "parents": parents,
            "min_samples": min_samples,
            "seed": seed,
        },
        "stages": {
            "stage1": s1,
            "stage2": out["stage2"],
            "stage3": out["stage3"],
        },
        "records_to_active_s": wall1,
        "regression_to_rollback_s": float(out["stage2"]["wall_s"]),
        "bounce_resume_s": float(out["stage3"]["wall_s"]),
        "records_per_sec": round(fed / wall1, 1),
        "drill_ok": bool(out["ok"]),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--epoch-records", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--announces", type=int, default=80,
                   help="shadow announce groups generated per pump")
    p.add_argument("--parents", type=int, default=6)
    p.add_argument("--min-samples", type=int, default=200,
                   help="shadow/canary joined-sample floors")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.epoch_records, args.batch_size = 128, 32
        args.announces, args.parents = 24, 4
        args.min_samples = 40
    try:
        out = run(args.epoch_records, args.batch_size, args.announces,
                  args.parents, args.min_samples, args.seed)
        missing = [k for k in SCHEMA_KEYS if k not in out]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
        if not out["drill_ok"]:
            raise RuntimeError(f"drill failed: {json.dumps(out['stages'])[:200]}")
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "lifecycle_records_to_active_seconds",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
