"""Benchmark: flagship ranker training records/sec/chip (BASELINE.md headline).

Flagship = the hop-feature parent-peer ranker (models/hop.py): neighbor
aggregation precomputed per graph snapshot, train step is pure dense MXU
work on edge batches.  Chosen over the round-1 GAT flagship on MEASURED
evidence (BENCHMARKS.md): identical config[2] workload gives val log-MAE
0.505 (hop) vs 0.514 (GAT) while the step drops ~93 ms → ~3 ms — the GAT
step is floored by XLA's sort-based scatter in the neighbor-gather
backward (~22 ms/layer), which no in-step rewiring beat.

Flagship WIDTH = hidden 1024, promoted per the r2 verdict's rule on
MEASURED quality evidence (tools/ablate_width.py, dropout ON, exact
config[2] workload): val log-MAE 0.5050 / F1 0.7964 at hidden 1024
vs 0.5067 / 0.7943 at the old hidden-128 flagship — the compute-bound
width is BETTER on quality, and it runs the MXU at the ≥30%-MFU
north-star bar instead of sitting on the HBM bandwidth floor.

vs_baseline is measured against the north-star requirement
(BASELINE.json): 1B records / 10 min on v5e-16 ⇒ ~104,167 records/sec/chip.
The reference itself publishes no numbers (its trainer is a stub —
trainer/training/training.go:82-99), so the north-star rate is the bar.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

import numpy as np

# North star: 1e9 records / 600 s / 16 chips.
BASELINE_RECORDS_PER_SEC_PER_CHIP = 1e9 / 600.0 / 16.0

# Headline regression guard: warn when a fresh round lands more than
# this far below the last good recorded round (BENCH_r*.json).
REGRESSION_WARN_FRACTION = 0.20


def last_good_headline(repo_dir: str = None) -> dict:
    """The most recent BENCH_r*.json whose round produced a parsed
    headline value (rounds lost to backend errors/skips are passed
    over).  Returns {} when no good round exists."""
    repo_dir = repo_dir or os.path.dirname(os.path.abspath(__file__))
    best = {}
    for path in glob.glob(os.path.join(repo_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = data.get("parsed") or {}
        value = parsed.get("value")
        if value is None:
            continue
        # Only TPU rounds carry the headline: a CPU-fallback round (no
        # TPU plugin in the container) is a smoke artifact, never the
        # bar future rounds get judged against.  Legacy rounds predate
        # the backend field and were all TPU.
        if parsed.get("backend", "tpu") != "tpu":
            continue
        n = int(m.group(1))
        if not best or n > best["round"]:
            best = {"round": n, "value": float(value), "file": os.path.basename(path)}
    return best


def apply_regression_guard(out: dict, last_good: dict = None) -> dict:
    """Annotate a result line with the last-good headline and a warning
    flag when the fresh value regressed >20% against it — the perf
    trajectory's tripwire (the r03/r04 headline held ~4.8-4.9M
    rec/s/chip; a silent slide below that band should be loud in the
    artifact, not discovered rounds later)."""
    if last_good is None:
        last_good = last_good_headline()
    if not last_good:
        return out
    out["last_good"] = last_good
    value = out.get("value")
    if value is not None and value < (1.0 - REGRESSION_WARN_FRACTION) * last_good["value"]:
        out["regression_warning"] = {
            "dropped_to": round(value / last_good["value"], 3),
            "vs_round": last_good["round"],
        }
    return out


def _default_backend_init():
    """Force JAX runtime/device acquisition (the step that throws when
    the TPU runtime is busy/unreachable)."""
    import jax

    jax.devices()
    return jax


def _failure_class(exc: BaseException) -> str:
    """Coarse, grep-stable failure taxonomy for the one JSON line."""
    text = f"{type(exc).__name__}: {exc}".lower()
    if "unavailable" in text or isinstance(exc, ConnectionError):
        return "backend_unavailable"
    if isinstance(exc, TimeoutError) or "deadline" in text:
        return "backend_timeout"
    return type(exc).__name__


def acquire_backend(
    init=_default_backend_init,
    *,
    attempts: int = 4,
    base_delay: float = 0.5,
    max_delay: float = 4.0,
    sleep=time.sleep,
):
    """Backend init with bounded exponential backoff: a TRANSIENT
    UNAVAILABLE from a busy TPU runtime (the round-5 benchmark artifact
    was lost to exactly one un-retried instance of it) gets retried;
    persistent failure raises to main(), which emits ONE structured
    JSON line instead of a traceback so the harness always has a
    parseable artifact."""
    from dragonfly2_tpu.rpc.retry import retry_call

    return retry_call(
        init,
        attempts=attempts,
        base_delay=base_delay,
        max_delay=max_delay,
        retry_on=(RuntimeError, ConnectionError, TimeoutError, OSError),
        sleep=sleep,
    )


def main(acquire=acquire_backend) -> int:
    # EVERY backend touch — acquisition AND the benchmark body (device
    # queries, device_put, compiles, chain runs) — sits inside the
    # structured-failure path: a backend UNAVAILABLE at any point emits
    # the single parseable ok:false line, never a raw traceback (the
    # round-5 artifact was lost to a post-acquire jax.devices() call
    # dying outside this net).
    #
    # An unavailable/timed-out backend is a SKIP, not a failure: the
    # retried bring-up exhausted its backoff against hardware we cannot
    # will into existence, so the line carries "skipped" and the exit
    # code stays 0 — a BENCH_r05-style lost round shows up as one
    # parseable skip artifact the next round can retry, never an rc=1
    # that reads like a perf regression.
    try:
        jax = acquire()
        _run_benchmark(jax)
    except Exception as exc:  # noqa: BLE001 — report, never traceback
        failure = _failure_class(exc)
        out = {
            "ok": False,
            "metric": "hop_ranker_train_records_per_sec_per_chip",
            "failure": failure,
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }
        if failure in ("backend_unavailable", "backend_timeout"):
            out["skipped"] = failure
            print(json.dumps(out))
            return 0
        print(json.dumps(out))
        return 1
    return 0


def _run_benchmark(jax) -> None:

    # TPU-native PRNG for the dropout masks: threefry spends ~13 ms of the
    # hidden-1024 step generating bits; rbg (the hardware generator) cuts
    # the step 40.5→27.4 ms and lifts MFU 32→46% with quality HELD —
    # config[2] ablation at h1024: val MAE 0.5058/F1 0.7959 (rbg) vs
    # 0.5050/0.7964 (threefry), both better than the old h128 flagship's
    # 0.5067 (tools/ablate_width.py under JAX_DEFAULT_PRNG_IMPL).
    jax.config.update("jax_default_prng_impl", "rbg")
    import jax.numpy as jnp

    from dragonfly2_tpu.models import (
        HopConfig,
        HopRanker,
        build_neighbor_table,
        precompute_hop_features,
    )
    from dragonfly2_tpu.parallel.mesh import batch_sharding, create_mesh, replicated
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.train import (
        TrainConfig,
        TrainState,
        _graph_train_step,
        _make_optimizer,
    )

    n_devices = len(jax.devices())
    on_tpu = jax.devices()[0].platform != "cpu"

    # Workload at the north-star's shape: 100k-node probe graph (BASELINE
    # "1B records over a 100k-node peer graph"), K=16 neighbors, 128k-edge
    # batches. CPU fallback shrinks for CI smoke only.
    n_nodes = 100_000 if on_tpu else 4096
    batch = 131_072 if on_tpu else 8192
    cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
    avg_degree = 16
    density = avg_degree / max(n_nodes - 1, 1)
    src, dst, rtt = cluster.probe_edges(density=density, seed=0)
    table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=16)
    node_feats = jnp.asarray(cluster._host_feature_matrix())

    # Production flagship config: hidden 1024 (quality-validated width,
    # see module docstring), 2 hops, embed 32, dropout ON.
    mcfg = HopConfig(hidden=1024)
    hop_feats = jax.jit(lambda nf, t: precompute_hop_features(nf, t, hops=mcfg.hops))(
        node_feats, table
    )

    rng = np.random.default_rng(0)
    e_src = rng.integers(0, n_nodes, batch).astype(np.int32)
    e_dst = (e_src + rng.integers(1, n_nodes, batch).astype(np.int32)) % n_nodes
    bw = cluster._bandwidth_vec(e_src, e_dst)
    target = np.log1p(bw).astype(np.float32)

    model = HopRanker(mcfg)
    params = model.init(
        jax.random.PRNGKey(0),
        hop_feats,
        table,
        jnp.asarray(e_src[:2]),
        jnp.asarray(e_dst[:2]),
    )["params"]
    cfg = TrainConfig()
    state = TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=_make_optimizer(cfg, 100),
        dropout_rng=jax.random.PRNGKey(1),
    )

    mesh = create_mesh()
    repl = replicated(mesh)
    data_shard = batch_sharding(mesh)
    state = jax.device_put(state, repl)
    hop_feats = jax.device_put(hop_feats, repl)
    table = jax.device_put(table, repl)

    # Timing methodology: the device may sit behind a high-latency relay
    # where per-call dispatch costs ~100 ms and block_until_ready does not
    # guarantee execution completed.  So N steps run INSIDE one jit via
    # fori_loop (sequentially dependent through the carried state), a
    # scalar fetch forces full sync, and the per-step time is the slope
    # between two chain lengths — RTT and dispatch cancel out.  The fetch
    # touches a real param so the loop body survives dead-code elimination.
    from functools import partial

    @partial(jax.jit, static_argnums=(6,), in_shardings=(
        repl, repl, repl, data_shard, data_shard, data_shard
    ), out_shardings=repl)
    def run_chain(s, nf, t, a, b, y, n):
        def body(_, carry):
            new_s, _loss = _graph_train_step(carry, nf, t, a, b, y, None)
            return new_s
        final = jax.lax.fori_loop(0, n, body, s)
        return final.params["Dense_0"]["bias"][0]  # tiny sync handle

    a = jax.device_put(jnp.asarray(e_src), data_shard)
    b = jax.device_put(jnp.asarray(e_dst), data_shard)
    y = jax.device_put(jnp.asarray(target), data_shard)

    # Chain lengths sized to the step: the hidden-1024 step is ~60 ms, so
    # shorter chains than the 3 ms hidden-128 bench still dominate relay
    # jitter while keeping the bench under a minute.
    n_short, n_long = (4, 44) if on_tpu else (2, 8)
    float(run_chain(state, hop_feats, table, a, b, y, n_short))  # compile both
    float(run_chain(state, hop_feats, table, a, b, y, n_long))

    per_step = None
    for _ in range(3):
        t0 = time.perf_counter()
        float(run_chain(state, hop_feats, table, a, b, y, n_short))
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run_chain(state, hop_feats, table, a, b, y, n_long))
        t_long = time.perf_counter() - t0
        est = max((t_long - t_short) / (n_long - n_short), 1e-9)
        per_step = est if per_step is None else min(per_step, est)

    records_per_sec_per_chip = batch / per_step / n_devices

    # MFU from XLA's own cost model. Cost the train step DIRECTLY (not the
    # chain): HloCostAnalysis counts a while-loop body once regardless of
    # trip count, so dividing chain flops by chain length under-reports by
    # the chain length (round-1 bench reported 0.51% where the true figure
    # was ~2.5%).
    mfu = None
    try:
        step_jit = jax.jit(
            lambda s, nf, t, aa, bb, yy: _graph_train_step(s, nf, t, aa, bb, yy, None)
        )
        cost = step_jit.lower(state, hop_feats, table, a, b, y).compile().cost_analysis()
        if cost and "flops" in cost:
            step_flops = float(cost["flops"])
            peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak; CPU nominal
            mfu = step_flops / per_step / peak
    except Exception:
        pass

    out = {
        "ok": True,
        "metric": "hop_ranker_train_records_per_sec_per_chip",
        "value": round(records_per_sec_per_chip, 1),
        "unit": "records/s/chip",
        "vs_baseline": round(
            records_per_sec_per_chip / BASELINE_RECORDS_PER_SEC_PER_CHIP, 3
        ),
        "step_ms": round(per_step * 1e3, 2),
        # The guard and future readers must know whether this round ran
        # on real hardware or the CPU smoke fallback.
        "backend": "tpu" if on_tpu else "cpu",
    }
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    # Compare against the last good recorded round: a >20% slide from
    # the standing headline gets flagged IN the artifact.
    apply_regression_guard(out)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
