"""Bring up the full cluster as OS processes and run the e2e loop —
the docker-compose topology without containers (CI / dev machines
without a docker daemon; the container path is deploy/docker-compose.yaml
with the SAME services and the SAME deploy/e2e_loop.py).

  python deploy/run_local.py          # exit 0 = cluster up + loop passed
  python deploy/run_local.py --mtls   # same, with auto-issued mTLS on the
                                      # piece plane (manager-hosted CA)
  python deploy/run_local.py --replicas N
                                      # N scheduler replicas: daemons
                                      # steer tasks by consistent hash,
                                      # probe graph shared via the manager
"""

from __future__ import annotations

import os
import re
import select
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PIECE = 64 * 1024


def main() -> int:
    mtls = "--mtls" in sys.argv[1:]
    # --manager-standby: launch a leader+hot-standby manager pair
    # (manager/replication.py); clients get BOTH urls and fail over.
    manager_standby = "--manager-standby" in sys.argv[1:]
    replicas = 1
    argv = sys.argv[1:]
    if "--replicas" in argv:
        i = argv.index("--replicas")
        # Value optional: bare "--replicas" means 2.
        if i + 1 < len(argv) and argv[i + 1].isdigit():
            replicas = max(int(argv[i + 1]), 1)
        else:
            replicas = 2
    tmp = tempfile.mkdtemp(prefix="df-local-")
    # Hermetic JAX: the harness only needs CPU (the trainer's TPU path is
    # exercised by bench.py / the driver); inheriting an ambient
    # accelerator-plugin env without its plugin path would crash training.
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    procs = []

    def write(name: str, text: str) -> str:
        path = os.path.join(tmp, name)
        with open(path, "w") as f:
            f.write(text)
        return path

    def spawn(name, argv, ready_prefixes, extra_env=None):
        proc = subprocess.Popen(
            [sys.executable, "-m", *argv],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**env, **(extra_env or {})},
        )
        procs.append(proc)
        # A reader THREAD owns the pipe: mixing select() on the fd with
        # buffered readline() can strand a ready line in the Python-side
        # buffer (stderr is merged, so log lines coalesce with it in one
        # OS read) and falsely declare the service dead.
        import queue

        # Bounded: after readiness nobody consumes — the pump drops the
        # oldest instead of retaining every log line for the cluster's
        # lifetime, and keeps reading so the child never blocks on a
        # full pipe.
        lines: "queue.Queue" = queue.Queue(maxsize=1000)

        def pump() -> None:
            for raw in proc.stdout:
                while True:
                    try:
                        lines.put_nowait(raw)
                        break
                    except queue.Full:
                        try:
                            lines.get_nowait()
                        except queue.Empty:
                            pass

        threading.Thread(target=pump, name=f"pump-{name}", daemon=True).start()
        found = {}
        deadline = time.time() + 60
        while time.time() < deadline and len(found) < len(ready_prefixes):
            try:
                line = lines.get(timeout=max(deadline - time.time(), 0.1)).strip()
            except queue.Empty:
                break
            for p in ready_prefixes:
                if line.startswith(p):
                    found[p] = line
        if len(found) != len(ready_prefixes):
            raise SystemExit(f"run_local: {name} never became ready ({found})")
        print(f"run_local: {name} up", flush=True)
        return found

    try:
        # The HA pair shares a generated lease secret (config validation
        # refuses the public default — it would let anyone forge leases
        # or fetch the replicated state).
        import secrets as _secrets

        ha_yaml = (
            "ha: {enable: true, lease_ttl_s: 5.0, "
            f"lease_secret: {_secrets.token_hex(16)}}}\n"
        )
        mcfg = write("manager.yaml", (
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            f"registry: {{blob_dir: {tmp}/manager}}\n"
            + (ha_yaml if manager_standby else "")
            + (f"ca_dir: {tmp}/ca\n" if mtls else "")
        ))
        mout = spawn("manager", ["dragonfly2_tpu.cli.manager", "--config", mcfg],
                     ["manager: serving"])
        manager_url = re.search(r"REST on (\S+)", mout["manager: serving"]).group(1)
        manager_urls = manager_url
        if manager_standby:
            sbmcfg = write("manager-standby.yaml", (
                "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
                f"registry: {{blob_dir: {tmp}/manager-standby}}\n"
                + ha_yaml
            ))
            sbout = spawn(
                "manager-standby",
                ["dragonfly2_tpu.cli.manager", "--config", sbmcfg,
                 "--replicate-from", manager_url],
                ["manager: serving"],
            )
            standby_url = re.search(
                r"REST on (\S+)", sbout["manager: serving"]
            ).group(1)
            # Every manager client takes the pair: comma-separated spec
            # feeds rpc/resolver.ManagerEndpoints.
            manager_urls = f"{manager_url},{standby_url}"

        tcfg = write("trainer.yaml", (
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            f"data_dir: {tmp}/trainer\n"
            "training: {epochs: 6, learning_rate: 0.003, warmup_steps: 10}\n"
        ))
        tout = spawn("trainer",
                     ["dragonfly2_tpu.cli.trainer", "--config", tcfg,
                      "--manager", manager_url],
                     ["trainer: ingest"])
        trainer_url = re.search(r"ingest on (\S+?)[, ]",
                                tout["trainer: ingest"] + " ").group(1)

        scfg = write("scheduler.yaml", (
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            "scheduling: {retry_interval_s: 0.1}\n"
            f"storage: {{dir: {tmp}/records, buffer_size: 1}}\n"
            f"manager_addr: {manager_urls}\n"
            "dynconfig_refresh_s: 5.0\n"
            + ("topology_sync_interval_s: 3.0\n" if replicas > 1
               else "topology_sync_interval_s: 10.0\n")
            + ("security: {auto_issue: true}\n" if mtls else "")
        ))
        sout = spawn("scheduler",
                     ["dragonfly2_tpu.cli.scheduler", "--config", scfg],
                     ["scheduler: serving"])
        scheduler_url = re.search(r"rpc on (\S+?),",
                                  sout["scheduler: serving"] + ",").group(1)
        replica_urls = []
        for n in range(1, replicas):
            # Replica N: same manager, own storage — the probe graph
            # crosses replicas only through the manager's topology sync.
            sbcfg = write(f"scheduler-{n}.yaml", (
                "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
                "scheduling: {retry_interval_s: 0.1}\n"
                f"storage: {{dir: {tmp}/records-{n}, buffer_size: 1}}\n"
                f"manager_addr: {manager_urls}\n"
                "dynconfig_refresh_s: 5.0\n"
                "topology_sync_interval_s: 3.0\n"
                + ("security: {auto_issue: true}\n" if mtls else "")
            ))
            sbout = spawn(f"scheduler-{n}",
                          ["dragonfly2_tpu.cli.scheduler", "--config", sbcfg],
                          ["scheduler: serving"])
            replica_urls.append(re.search(
                r"rpc on (\S+?),", sbout["scheduler: serving"] + ","
            ).group(1))
        scheduler_b_url = replica_urls[0] if replica_urls else ""

        # Auto-issued mTLS: every daemon bootstraps its identity from the
        # manager's cluster CA at boot; the piece plane then moves bytes
        # over mutual TLS end to end (certify analog).
        mtls_yaml = (
            f"manager_addr: {manager_url}\nsecurity: {{auto_issue: true}}\n"
            if mtls else ""
        )
        seedcfg = write("seed.yaml", (
            "server: {host: 127.0.0.1, port: 0, advertise_ip: 127.0.0.1}\n"
            f"storage: {{dir: {tmp}/seed}}\n"
            f"piece_size: {PIECE}\n"
            + mtls_yaml
        ))
        daemon_scheduler_arg = ",".join([scheduler_url] + replica_urls)
        spawn("seed",
              ["dragonfly2_tpu.cli.dfdaemon", "--scheduler", daemon_scheduler_arg,
               "--config", seedcfg, "--seed-peer"],
              ["dfdaemon: serving"],
              {"DF_DAEMON_STATE": f"{tmp}/seed.json"})

        controls = {}
        for name, port in (("daemon-a", 0), ("daemon-b", 0)):
            dcfg = write(f"{name}.yaml", (
                "server: {host: 127.0.0.1, port: 0, advertise_ip: 127.0.0.1}\n"
                f"storage: {{dir: {tmp}/{name}}}\n"
                f"piece_size: {PIECE}\n"
                + mtls_yaml
            ))
            dout = spawn(name,
                         ["dragonfly2_tpu.cli.dfdaemon", "--scheduler",
                          daemon_scheduler_arg, "--config", dcfg],
                         ["dfdaemon: serving"],
                         {"DF_DAEMON_STATE": f"{tmp}/{name}.json"})
            controls[name] = re.search(
                r"control (\S+?)[, ]", dout["dfdaemon: serving"] + " "
            ).group(1)

        print("run_local: cluster up, running e2e loop", flush=True)
        # Ephemeral origin port: concurrent runs on one machine (CI + a
        # dev shell) must not collide on a fixed port.
        import socket as _socket

        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        origin_port = probe.getsockname()[1]
        probe.close()
        e2e_env = {
            **env,
            "MANAGER_URL": manager_url,
            "MANAGER_URLS": manager_urls,
            "SCHEDULER_URL": scheduler_url,
            "SCHEDULER_B_URL": scheduler_b_url,
            "TRAINER_URL": trainer_url,
            "DAEMON_A_CONTROL": controls["daemon-a"],
            "DAEMON_B_CONTROL": controls["daemon-b"],
            "ORIGIN_BIND": f"127.0.0.1:{origin_port}",
            "ORIGIN_URL": f"http://127.0.0.1:{origin_port}",
        }
        rc = subprocess.call(
            [sys.executable, os.path.join(REPO, "deploy", "e2e_loop.py")],
            env=e2e_env,
        )
        print(f"run_local: e2e exit {rc}", flush=True)
        return rc
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + 5
        for p in procs:
            try:
                p.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
