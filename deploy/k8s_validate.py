"""Offline structural validation for deploy/k8s/*.yaml (VERDICT r4 #4).

kubeconform validates manifests against the upstream-generated OpenAPI
schemas; this sandbox has no egress, so the schema subset for every
kind/field the manifests use is VENDORED here as strict structural
checks — unknown keys at checked levels, wrong types, bad enum values,
out-of-range ports, selector/label mismatches and dangling volume
references all fail.  That is deliberately stronger than the old string
asserts (a bad ``apiVersion`` or a field nested one level too deep used
to pass CI) and deliberately weaker than a live API server: admission,
defaulting, RBAC and scheduling only exist on a real cluster — see
deploy/README.md for what still needs one.

Also exposes the NORMALIZED deployment topology of both the k8s
manifests and docker-compose.yaml so tests diff them programmatically
(same entry modules, same config files, same ports) instead of by
substring.

Usage:
  python deploy/k8s_validate.py deploy/k8s/dragonfly.yaml   # exit 1 on errors
"""

from __future__ import annotations

import re
import sys
from typing import Any, Dict, List

import yaml

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_QUANTITY = re.compile(r"^[0-9]+(\.[0-9]+)?(m|k|Ki|M|Mi|G|Gi|T|Ti|P|Pi|E|Ei)?$")

# kind → the apiVersion the cluster serves it under (a wrong pair is the
# single most common manifest rot: removed beta groups).
KIND_API = {
    "Service": "v1",
    "ConfigMap": "v1",
    "Deployment": "apps/v1",
    "StatefulSet": "apps/v1",
    "DaemonSet": "apps/v1",
}

WORKLOAD_KINDS = ("Deployment", "StatefulSet", "DaemonSet")


class _Ctx:
    def __init__(self) -> None:
        self.errors: List[str] = []

    def err(self, path: str, msg: str) -> None:
        self.errors.append(f"{path}: {msg}")


def _check_keys(ctx: _Ctx, path: str, obj: Any, allowed: set, required: set):
    if not isinstance(obj, dict):
        ctx.err(path, f"expected mapping, got {type(obj).__name__}")
        return False
    for k in obj:
        if k not in allowed:
            ctx.err(path, f"unknown field {k!r} (allowed: {sorted(allowed)})")
    for k in required:
        if k not in obj:
            ctx.err(path, f"missing required field {k!r}")
    return True


def _check_labels(ctx: _Ctx, path: str, labels: Any) -> None:
    if not isinstance(labels, dict) or not all(
        isinstance(k, str) and isinstance(v, str) for k, v in labels.items()
    ):
        ctx.err(path, "labels must be a string→string map")


def _check_port_number(ctx: _Ctx, path: str, v: Any) -> None:
    if not isinstance(v, int) or isinstance(v, bool) or not 1 <= v <= 65535:
        ctx.err(path, f"port must be an int in [1, 65535], got {v!r}")


def _check_metadata(ctx: _Ctx, path: str, meta: Any) -> None:
    if not _check_keys(
        ctx, path, meta, {"name", "labels", "namespace", "annotations"},
        {"name"},
    ):
        return
    name = meta.get("name")
    if not isinstance(name, str) or not _DNS1123.match(name or ""):
        ctx.err(path + ".name", f"{name!r} is not a DNS-1123 label")
    if "labels" in meta:
        _check_labels(ctx, path + ".labels", meta["labels"])


def _check_probe(ctx: _Ctx, path: str, probe: Any) -> None:
    ok = _check_keys(
        ctx, path, probe,
        {"httpGet", "tcpSocket", "exec", "periodSeconds",
         "initialDelaySeconds", "timeoutSeconds", "failureThreshold"},
        set(),
    )
    if not ok:
        return
    if "httpGet" in probe and _check_keys(
        ctx, path + ".httpGet", probe["httpGet"], {"path", "port", "scheme"},
        {"path", "port"},
    ):
        port = probe["httpGet"]["port"]
        if isinstance(port, int):
            _check_port_number(ctx, path + ".httpGet.port", port)


def _check_container(ctx: _Ctx, path: str, c: Any, volumes: set) -> None:
    if not _check_keys(
        ctx, path, c,
        {"name", "image", "command", "args", "ports", "env", "volumeMounts",
         "readinessProbe", "livenessProbe", "resources", "workingDir"},
        {"name", "image"},
    ):
        return
    if "command" in c and not (
        isinstance(c["command"], list)
        and all(isinstance(x, str) for x in c["command"])
    ):
        ctx.err(path + ".command", "must be a list of strings")
    for i, p in enumerate(c.get("ports", [])):
        pp = f"{path}.ports[{i}]"
        if _check_keys(ctx, pp, p, {"containerPort", "name", "protocol",
                                    "hostPort"}, {"containerPort"}):
            _check_port_number(ctx, pp + ".containerPort", p["containerPort"])
    for i, m in enumerate(c.get("volumeMounts", [])):
        mp = f"{path}.volumeMounts[{i}]"
        if _check_keys(ctx, mp, m, {"name", "mountPath", "readOnly",
                                    "subPath"}, {"name", "mountPath"}):
            if m["name"] not in volumes:
                ctx.err(mp, f"mounts volume {m['name']!r} that the pod "
                            f"spec does not define")
    for probe in ("readinessProbe", "livenessProbe"):
        if probe in c:
            _check_probe(ctx, f"{path}.{probe}", c[probe])


def _check_pod_spec(ctx: _Ctx, path: str, spec: Any,
                    *, extra_volumes: set = frozenset()) -> None:
    if not _check_keys(
        ctx, path, spec,
        {"containers", "initContainers", "volumes", "hostNetwork",
         "nodeSelector", "tolerations", "serviceAccountName",
         "terminationGracePeriodSeconds"},
        {"containers"},
    ):
        return
    volumes = set(extra_volumes)
    for i, v in enumerate(spec.get("volumes", [])):
        vp = f"{path}.volumes[{i}]"
        if _check_keys(ctx, vp, v, {"name", "configMap", "emptyDir",
                                    "hostPath", "secret",
                                    "persistentVolumeClaim"}, {"name"}):
            volumes.add(v["name"])
            if "configMap" in v:
                _check_keys(ctx, vp + ".configMap", v["configMap"],
                            {"name", "items", "optional"}, {"name"})
    if not spec.get("containers"):
        ctx.err(path + ".containers", "must be a non-empty list")
        return
    for i, c in enumerate(spec["containers"]):
        _check_container(ctx, f"{path}.containers[{i}]", c, volumes)


def _check_workload(ctx: _Ctx, path: str, doc: Dict[str, Any]) -> None:
    kind = doc["kind"]
    # Per-kind field sets: Deployments roll with `strategy`, the other
    # two with `updateStrategy`; serviceName/volumeClaimTemplates are
    # StatefulSet-only — a real apiserver rejects the cross-kind mixups.
    allowed = {"selector", "template", "minReadySeconds",
               "revisionHistoryLimit"}
    if kind != "DaemonSet":
        allowed.add("replicas")  # a real apiserver rejects it on DaemonSet
    if kind == "Deployment":
        allowed |= {"strategy", "paused", "progressDeadlineSeconds"}
    else:
        allowed.add("updateStrategy")
    if kind == "StatefulSet":
        allowed |= {"serviceName", "volumeClaimTemplates",
                    "podManagementPolicy"}
    required = {"selector", "template"}
    if kind == "StatefulSet":
        required.add("serviceName")
    spec = doc.get("spec")
    if not _check_keys(ctx, path + ".spec", spec, allowed, required):
        return
    if kind == "DaemonSet" and "replicas" in spec:
        ctx.err(path + ".spec.replicas", "DaemonSet has no replicas field")
    if kind != "DaemonSet" and "replicas" in spec:
        r = spec["replicas"]
        if not isinstance(r, int) or isinstance(r, bool) or r < 0:
            ctx.err(path + ".spec.replicas", f"must be a non-negative int, got {r!r}")
    sel = spec.get("selector")
    match = None
    if _check_keys(ctx, path + ".spec.selector", sel,
                   {"matchLabels", "matchExpressions"}, {"matchLabels"}):
        match = sel.get("matchLabels")
        _check_labels(ctx, path + ".spec.selector.matchLabels", match)
    tmpl = spec.get("template")
    if not isinstance(tmpl, dict):
        ctx.err(path + ".spec.template", "missing/invalid pod template")
        return
    meta = tmpl.get("metadata", {})
    labels = meta.get("labels", {}) if isinstance(meta, dict) else {}
    _check_labels(ctx, path + ".spec.template.metadata.labels", labels)
    if isinstance(match, dict) and isinstance(labels, dict):
        for k, v in match.items():
            if labels.get(k) != v:
                ctx.err(
                    path + ".spec.selector",
                    f"matchLabels {k}={v!r} not present on the pod "
                    f"template labels {labels!r} — the workload would "
                    f"select none of its own pods",
                )
    pvc_names = set()
    for i, vct in enumerate(spec.get("volumeClaimTemplates", [])):
        vp = f"{path}.spec.volumeClaimTemplates[{i}]"
        if not _check_keys(ctx, vp, vct, {"metadata", "spec"},
                           {"metadata", "spec"}):
            continue
        if not isinstance(vct["metadata"], dict):
            ctx.err(vp + ".metadata", "expected mapping")
            continue
        pvc_names.add(vct["metadata"].get("name"))
        vspec = vct["spec"]
        if _check_keys(ctx, vp + ".spec", vspec,
                       {"accessModes", "resources", "storageClassName"},
                       {"accessModes", "resources"}):
            modes = vspec["accessModes"]
            if not isinstance(modes, list):
                ctx.err(vp + ".spec.accessModes", "expected list")
                modes = []
            for m in modes:
                if m not in ("ReadWriteOnce", "ReadOnlyMany",
                             "ReadWriteMany", "ReadWriteOncePod"):
                    ctx.err(vp + ".spec.accessModes", f"bad mode {m!r}")
            res = vspec["resources"]
            storage = (
                res.get("requests", {}).get("storage")
                if isinstance(res, dict) and isinstance(
                    res.get("requests", {}), dict
                )
                else None
            )
            if not isinstance(storage, str) or not _QUANTITY.match(storage):
                ctx.err(vp + ".spec.resources.requests.storage",
                        f"bad quantity {storage!r}")
    _check_pod_spec(ctx, path + ".spec.template.spec", tmpl.get("spec"),
                    extra_volumes=pvc_names)


def _check_service(ctx: _Ctx, path: str, doc: Dict[str, Any]) -> None:
    spec = doc.get("spec")
    if not _check_keys(
        ctx, path + ".spec", spec,
        {"selector", "ports", "clusterIP", "type", "sessionAffinity"},
        {"ports"},
    ):
        return
    if "selector" in spec and not isinstance(spec["selector"], dict):
        ctx.err(path + ".spec.selector", "must be a string→string map")
    cip = spec.get("clusterIP")
    if cip is not None and cip != "None" and not re.match(
        r"^\d+\.\d+\.\d+\.\d+$", str(cip)
    ):
        ctx.err(path + ".spec.clusterIP",
                f"must be 'None' or an IP, got {cip!r}")
    for i, p in enumerate(spec.get("ports", [])):
        pp = f"{path}.spec.ports[{i}]"
        if _check_keys(ctx, pp, p, {"name", "port", "targetPort",
                                    "protocol", "nodePort"}, {"port"}):
            _check_port_number(ctx, pp + ".port", p["port"])
            tp = p.get("targetPort")
            if isinstance(tp, int):
                _check_port_number(ctx, pp + ".targetPort", tp)


def validate_documents(docs: List[Dict[str, Any]]) -> List[str]:
    """Structural validation of a manifest list; returns error strings."""
    ctx = _Ctx()
    seen = set()
    for idx, doc in enumerate(docs):
        if not isinstance(doc, dict):
            ctx.err(f"doc[{idx}]", "not a mapping")
            continue
        kind = doc.get("kind")
        name = (doc.get("metadata") or {}).get("name", "?")
        path = f"{kind}/{name}"
        top = {"apiVersion", "kind", "metadata"}
        top |= {"data", "binaryData", "immutable"} if kind == "ConfigMap" \
            else {"spec"}
        if not _check_keys(ctx, path, doc, top,
                           {"apiVersion", "kind", "metadata"}):
            continue
        if kind not in KIND_API:
            ctx.err(path, f"unsupported kind {kind!r} (vendored schema set: "
                          f"{sorted(KIND_API)})")
            continue
        if doc["apiVersion"] != KIND_API[kind]:
            ctx.err(path + ".apiVersion",
                    f"{doc['apiVersion']!r} — {kind} is served under "
                    f"{KIND_API[kind]!r}")
        _check_metadata(ctx, path + ".metadata", doc.get("metadata"))
        key = (kind, name)
        if key in seen:
            ctx.err(path, "duplicate kind/name")
        seen.add(key)
        if kind in WORKLOAD_KINDS:
            _check_workload(ctx, path, doc)
        elif kind == "Service":
            _check_service(ctx, path, doc)
        elif kind == "ConfigMap":
            data = doc.get("data") or {}  # bare `data:` parses to None
            if not isinstance(data, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in data.items()
            ):
                ctx.err(path + ".data",
                        "must be a string→string map (a mis-indented "
                        "value becomes a nested mapping)")

    # Cross-document: every Service selector must select at least one
    # workload pod template (a dangling selector routes nothing).
    pods = []
    for doc in docs:
        if isinstance(doc, dict) and doc.get("kind") in WORKLOAD_KINDS:
            try:
                pods.append(
                    doc["spec"]["template"]["metadata"]["labels"]
                )
            except (KeyError, TypeError):
                pass
    for doc in docs:
        if not (isinstance(doc, dict) and doc.get("kind") == "Service"):
            continue
        spec = doc.get("spec")
        sel = spec.get("selector") if isinstance(spec, dict) else None
        if not sel:
            continue
        if not isinstance(sel, dict):
            ctx.err(
                f"Service/{doc.get('metadata', {}).get('name', '?')}"
                f".spec.selector",
                f"must be a string→string map, got {type(sel).__name__}",
            )
            continue
        if not any(
            isinstance(labels, dict)
            and all(labels.get(k) == v for k, v in sel.items())
            for labels in pods
        ):
            ctx.err(
                f"Service/{doc['metadata']['name']}.spec.selector",
                f"{sel!r} selects no workload pod template in this manifest",
            )
    return ctx.errors


# ---------------------------------------------------------------------------
# Normalized topology (for the programmatic compose diff)
# ---------------------------------------------------------------------------


def k8s_topology(docs: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """workload name → {module, config, ports, replicas} from manifests."""
    out: Dict[str, Dict[str, Any]] = {}
    for doc in docs:
        if not (isinstance(doc, dict) and doc.get("kind") in WORKLOAD_KINDS):
            continue
        c = doc["spec"]["template"]["spec"]["containers"][0]
        cmd = c.get("command", [])
        module = cmd[2] if cmd[:2] == ["python", "-m"] and len(cmd) > 2 else None
        config = None
        if "--config" in cmd:
            config = cmd[cmd.index("--config") + 1].rsplit("/", 1)[-1]
        out[doc["metadata"]["name"]] = {
            "kind": doc["kind"],
            "module": module,
            "config": config,
            "ports": sorted(p["containerPort"] for p in c.get("ports", [])),
            "replicas": doc["spec"].get("replicas", 1),
            "image": c.get("image"),
        }
    return out


def compose_topology(compose: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """service name → {module, config, ports} from docker-compose.yaml.
    Compose commands are the `python -m` image entrypoint's argv."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, svc in compose.get("services", {}).items():
        cmd = svc.get("command", [])
        module = cmd[0] if cmd and str(cmd[0]).startswith("dragonfly2_tpu.") else None
        config = None
        if "--config" in cmd:
            config = str(cmd[cmd.index("--config") + 1]).rsplit("/", 1)[-1]
        ports = []
        for p in svc.get("expose", []) or []:
            ports.append(int(p))
        for p in svc.get("ports", []) or []:
            ports.append(int(str(p).split(":")[-1]))
        out[name] = {
            "module": module,
            "config": config,
            "ports": sorted(set(ports)),
        }
    return out


def main(argv: List[str]) -> int:
    errors: List[str] = []
    for path in argv or ["deploy/k8s/dragonfly.yaml"]:
        with open(path) as f:
            docs = [d for d in yaml.safe_load_all(f) if d is not None]
        errs = validate_documents(docs)
        for e in errs:
            print(f"{path}: {e}")
        errors.extend(errs)
    if not errors:
        print("k8s manifests: structurally valid")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
