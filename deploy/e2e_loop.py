"""Cluster e2e: dfget + preheat + the train→activate→evaluator loop
against a COMPOSED cluster (reference: test/e2e run inside kind,
Makefile:358-366).

Addresses come from the environment, so the same script drives both the
docker-compose topology (service hostnames) and deploy/run_local.py's
process topology (loopback).  Exit code 0 = every stage passed.

Stages:
  1. liveness — manager /healthy, scheduler registered with the manager;
  2. back-to-source + P2P — daemon A pulls a blob from the origin,
     daemon B gets the same blob WITHOUT new origin fetches;
  3. preheat — a REST job fans to the scheduler's queue and the seed
     daemon warms the layer from the origin;
  4. learning loop — records stream to the trainer, the model lands in
     the MANAGER, REST activation flips it live, and a scheduler-side
     ML evaluator subscriber hot-swaps to the trained scorer;
  5. live cluster config — a PATCH on the manager changes the RUNNING
     scheduler's candidate-parent limit through dynconfig (observed on
     the scheduling wire, not just the config endpoint).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

MANAGER = os.environ.get("MANAGER_URL", "http://127.0.0.1:65003")
SCHEDULER = os.environ.get("SCHEDULER_URL", "http://127.0.0.1:8002")
DAEMON_A = os.environ.get("DAEMON_A_CONTROL", "http://127.0.0.1:65010")
DAEMON_B = os.environ.get("DAEMON_B_CONTROL", "http://127.0.0.1:65011")
ORIGIN_BIND = os.environ.get("ORIGIN_BIND", "127.0.0.1:8099")
ORIGIN_URL = os.environ.get("ORIGIN_URL", "http://127.0.0.1:8099")
PIECE = 64 * 1024
BLOB = bytes(i % 251 for i in range(4 * PIECE))


def log(msg: str) -> None:
    print(f"e2e: {msg}", flush=True)


def call(base, method, path, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json"}, method=method,
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def wait_for(what, fn, timeout=120):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            out = fn()
            if out:
                return out
            last = "falsy"
        except Exception as exc:  # noqa: BLE001 — booting cluster
            last = exc
        # 0.2s granularity: the loop runs ~20 waits per drill and a 1s
        # poll overshoots each by ~0.5s — pure dead time on local wires.
        time.sleep(0.2)
    raise SystemExit(f"e2e: TIMEOUT waiting for {what}: {last}")


class _Origin(BaseHTTPRequestHandler):
    hits = []

    def log_message(self, *args):
        pass

    def do_HEAD(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(BLOB)))
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):
        type(self).hits.append(self.path)
        rng = self.headers.get("Range")
        body, code = BLOB, 200
        if rng:
            s, e = rng.split("=", 1)[1].split("-")
            body = BLOB[int(s): (int(e) if e else len(BLOB) - 1) + 1]
            code = 206
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def main() -> int:
    host, port = ORIGIN_BIND.rsplit(":", 1)
    origin = ThreadingHTTPServer((host, int(port)), _Origin)
    threading.Thread(target=origin.serve_forever, daemon=True).start()

    # -- 1. liveness --------------------------------------------------------
    wait_for("manager", lambda: call(MANAGER, "GET", "/api/v1/healthy")["ok"])
    scheds = wait_for(
        "scheduler registration",
        lambda: call(MANAGER, "GET", "/api/v1/schedulers"),
    )
    sched_id = scheds[0]["id"]
    log(f"manager healthy; scheduler {sched_id} registered")
    wait_for("daemon A", lambda: call(DAEMON_A, "GET", "/healthy")["ok"])
    wait_for("daemon B", lambda: call(DAEMON_B, "GET", "/healthy")["ok"])

    # -- 2. back-to-source then P2P -----------------------------------------
    url = f"{ORIGIN_URL}/blob-1"
    r = call(DAEMON_A, "POST", "/download",
             {"url": url, "piece_size": PIECE}, timeout=120)
    assert r.get("ok"), r
    hits_after_seed = len(_Origin.hits)
    assert hits_after_seed > 0, "daemon A never reached the origin"
    log(f"daemon A seeded blob-1 ({r['pieces']} pieces, "
        f"{'source' if r.get('back_to_source') else 'p2p'})")

    r = call(DAEMON_B, "POST", "/download",
             {"url": url, "piece_size": PIECE}, timeout=120)
    assert r.get("ok"), r
    assert not r.get("back_to_source"), "daemon B fell back to source"
    assert len(_Origin.hits) == hits_after_seed, "P2P still hit the origin"
    log("daemon B fetched blob-1 P2P, origin untouched")

    # -- 3. preheat through the job plane -----------------------------------
    group = call(MANAGER, "POST", "/api/v1/jobs", {
        "type": "preheat",
        "args": {"urls": [f"{ORIGIN_URL}/layer-0"], "piece_size": PIECE},
        "queues": [f"scheduler:{sched_id}"],
    })
    state = wait_for(
        "preheat job",
        lambda: (lambda s: s if s["state"] in ("SUCCESS", "FAILURE") else None)(
            call(MANAGER, "GET", f"/api/v1/jobs/{group['group_id']}")
        ),
    )
    assert state["state"] == "SUCCESS", state
    log("preheat fanned to the scheduler queue and the seed daemon served it")

    # -- 4. the learning loop -----------------------------------------------
    # Records → trainer ingest → model in the MANAGER → activate → a
    # scheduler-side ML evaluator pulls the artifact (the evaluator seam).
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from dragonfly2_tpu.records.columnar import ColumnarWriter
    from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.rpc import RemoteRegistry, RemoteTrainer
    from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber

    trainer_url = os.environ.get("TRAINER_URL", "http://trainer:9090")
    shard = "/tmp/e2e_download.dfc"
    cluster = SyntheticCluster(num_hosts=64, seed=3)
    with ColumnarWriter(shard, DOWNLOAD_COLUMNS) as w:
        w.append(cluster.generate_feature_rows(2000, seed=7))
    trainer = RemoteTrainer(trainer_url, timeout=600)
    session = trainer.open_train_stream(
        ip="0.0.0.0", hostname="e2e", scheduler_id=sched_id
    )
    session.send_download_shard(shard)
    key = session.close_and_train()
    run = trainer.runs[key]
    assert run.error is None, run.error
    log(f"trainer run {key} finished")

    registry = RemoteRegistry(MANAGER)
    models = wait_for(
        "model in manager",
        lambda: registry.list(scheduler_id=sched_id, name="parent-bandwidth-mlp"),
    )
    registry.activate(models[0].id)
    active = registry.active_model(sched_id, "parent-bandwidth-mlp")
    assert active is not None and active.id == models[0].id
    evaluator = MLEvaluator()
    sub = ModelSubscriber(registry, evaluator, scheduler_id=sched_id)
    assert sub.refresh() is True and evaluator.has_model
    log(f"model v{active.version} activated; ML evaluator hot-swapped")

    # -- 5. live cluster config ----------------------------------------------
    # PATCH on the manager → the RUNNING scheduler's next pass caps
    # candidate parents at 1, observed via a real registration.
    from dragonfly2_tpu.rpc import RemoteScheduler
    from dragonfly2_tpu.scheduler.resource import Host

    call(MANAGER, "POST", "/api/v1/clusters/default:update",
         {"scheduler_cluster_config": {
             "candidate_parent_limit": 1, "filter_parent_limit": 15}})
    # Multi-replica: blob-1's swarm state lives on its consistent-hash
    # owner — register the probe peer THERE (any other replica answers
    # the wrong-shard steering redirect).  Ownership is the MANAGER's
    # published shard ring (DESIGN.md §24), the same map the shards'
    # guards enforce — never a locally invented hash.
    scheduler_for_blob1 = SCHEDULER
    if os.environ.get("SCHEDULER_B_URL"):
        from dragonfly2_tpu.scheduler.sharding import ShardRing
        from dragonfly2_tpu.utils import idgen

        published = ShardRing.from_payload(
            call(MANAGER, "GET", "/api/v1/clusters/default:config")
            ["scheduler_ring"]
        )
        scheduler_for_blob1 = published.url_of(
            published.owner(idgen.task_id(url))
        )
    client = RemoteScheduler(scheduler_for_blob1)
    probe_host = Host(id="e2e-probe", hostname="e2e-probe", ip="127.0.0.1",
                      download_port=1)

    def parents_now():
        reg = client.register_peer(host=probe_host, url=url)
        n = len(reg.schedule.parents) if reg.schedule else 0
        client.report_peer_failed(reg.peer)
        return n

    n_parents = wait_for(
        "live candidate limit", lambda: parents_now() == 1 and 1, timeout=60
    )
    log(f"cluster-config PATCH applied live: {n_parents} candidate parent")

    # -- 6. multi-replica: steering + cross-replica topology ----------------
    scheduler_b = os.environ.get("SCHEDULER_B_URL", "")
    if scheduler_b:
        from dragonfly2_tpu.scheduler.sharding import ShardRing
        from dragonfly2_tpu.utils import idgen

        ring_payload = call(
            MANAGER, "GET", "/api/v1/clusters/default:config"
        )["scheduler_ring"]
        assert len(ring_payload["members"]) == 2, ring_payload
        shard_ring = ShardRing.from_payload(ring_payload)
        # Find a blob whose task the PUBLISHED ring places on EACH
        # replica, download both through daemon A, and verify the swarm
        # state lives exactly on the ring-predicted owner (a child
        # registration there sees daemon A as a parent).
        owners = {}
        i = 0
        while len(set(owners.values())) < 2 and i < 64:
            name = f"steer-{i}"
            owners[name] = shard_ring.url_of(
                shard_ring.owner(idgen.task_id(f"{ORIGIN_URL}/{name}"))
            )
            i += 1
        assert len(set(owners.values())) == 2, "hash ring never split"
        picks = {}
        for name, owner in owners.items():
            if owner not in picks:
                picks[owner] = name
        for owner_url, name in picks.items():
            url2 = f"{ORIGIN_URL}/{name}"
            r = call(DAEMON_A, "POST", "/download",
                     {"url": url2, "piece_size": PIECE}, timeout=120)
            assert r.get("ok"), r
            owner_client = RemoteScheduler(owner_url)
            probe2 = Host(id=f"e2e-steer-{name}", hostname="e2e-steer",
                          ip="127.0.0.1", download_port=1)
            reg = owner_client.register_peer(host=probe2, url=url2)
            parent_hosts = {
                p.host.id for p in (reg.schedule.parents or [])
            } if reg.schedule and reg.schedule.parents else set()
            owner_client.report_peer_failed(reg.peer)
            assert parent_hosts, (
                f"task {name} not on its ring owner {owner_url}"
            )
        log(f"steering: tasks {sorted(picks.values())} landed on their "
            f"ring owners across 2 replicas")

        # A probe pushed to replica A becomes ranking input (folded RTT)
        # on replica B via the manager's shared-topology sync.
        a = RemoteScheduler(SCHEDULER)
        src = Host(id="e2e-prober", hostname="e2e-prober", ip="127.0.0.1",
                   download_port=1)
        dst = Host(id="e2e-probed", hostname="e2e-probed", ip="127.0.0.2",
                   download_port=1)
        a.announce_host(src)
        a.announce_host(dst)
        a.sync_probes_finished(src, [(dst.id, 7_500_000)])
        b = RemoteScheduler(scheduler_b)

        def rtt_on_b():
            out = b._call("topology_rtt", {"src": src.id, "dst": dst.id})
            return out.get("rtt_ns")

        rtt = wait_for("cross-replica topology sync", rtt_on_b, timeout=60)
        assert abs(rtt - 7_500_000) < 2_000_000, rtt
        log(f"probe pushed to replica A ranks on replica B (rtt {rtt} ns)")

    log("ALL STAGES PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
